"""Host-side scheduler cache + snapshot.

The analog of ``pkg/scheduler/backend/cache`` (cache.go:59 cacheImpl,
snapshot.go Snapshot): a mutable cache of nodes and assigned/assumed pods with
per-node aggregates, and an immutable point-in-time snapshot the scoring
kernels are generated from.

Semantics mirrored from the reference:
- ``assume_pod`` (cache.go:397 AssumePod): optimistically add the pod to its
  nominated node before the bind API call lands; ``finish_binding`` starts the
  expiry clock; ``forget_pod`` rolls back.
- ``update_snapshot`` (cache.go:190): incremental — only nodes whose
  generation advanced since the last snapshot are re-copied. The cache keeps
  a recency-ordered index of touched nodes so the per-cycle refresh walks
  only the Δ touched since the snapshot's watermark, not all N nodes.
- NodeInfo aggregates: ``requested`` (exact) and ``nonzero_requested``
  (scoring view with 100 mCPU / 200 MiB defaults,
  pkg/scheduler/util/pod_resources.go) are maintained on add/remove.
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass, field

from ..api import types as t


@dataclass
class NodeInfo:
    """Mutable per-node accounting — the analog of fwk.NodeInfo."""

    node: t.Node
    pods: dict[str, t.Pod] = field(default_factory=dict)  # uid -> pod
    requested: dict[str, int] = field(default_factory=dict)
    nonzero_requested: dict[str, int] = field(default_factory=dict)
    # refcounted (hostPort, protocol, hostIP) triples in use on this node
    # (fwk.NodeInfo.UsedPorts) — maintained here so the per-cycle port
    # encoding is O(nodes-with-ports), not O(all pods)
    port_triples: dict[tuple[int, str, str], int] = field(default_factory=dict)
    generation: int = 0

    def add_pod(self, pod: t.Pod) -> None:
        self.pods[pod.uid] = pod
        for k, v in pod.requests:
            self.requested[k] = self.requested.get(k, 0) + v
        for k, v in pod.nonzero_requests().items():
            self.nonzero_requested[k] = self.nonzero_requested.get(k, 0) + v
        for cp in pod.ports:
            if cp.host_port > 0:
                tr = (cp.host_port, cp.protocol or "TCP", cp.host_ip or "0.0.0.0")
                self.port_triples[tr] = self.port_triples.get(tr, 0) + 1

    def remove_pod(self, pod: t.Pod) -> None:
        if pod.uid not in self.pods:
            return
        del self.pods[pod.uid]
        for k, v in pod.requests:
            self.requested[k] = self.requested.get(k, 0) - v
        for k, v in pod.nonzero_requests().items():
            self.nonzero_requested[k] = self.nonzero_requested.get(k, 0) - v
        for cp in pod.ports:
            if cp.host_port > 0:
                tr = (cp.host_port, cp.protocol or "TCP", cp.host_ip or "0.0.0.0")
                left = self.port_triples.get(tr, 0) - 1
                if left > 0:
                    self.port_triples[tr] = left
                else:
                    self.port_triples.pop(tr, None)

    def clone(self) -> "NodeInfo":
        return NodeInfo(
            node=self.node,
            pods=dict(self.pods),
            requested=dict(self.requested),
            nonzero_requested=dict(self.nonzero_requested),
            port_triples=dict(self.port_triples),
            generation=self.generation,
        )


def _pod_has_affinity(pod: "t.Pod") -> bool:
    """podaffinity.has_any_affinity, inlined to avoid a cycle with the
    encoder import chain."""
    a = pod.affinity
    if a is None:
        return False
    pa, paa = a.pod_affinity, a.pod_anti_affinity
    return bool(
        (pa is not None and (pa.required or pa.preferred))
        or (paa is not None and (paa.required or paa.preferred))
    )


@dataclass
class Snapshot:
    """Immutable point-in-time view handed to the tensorizer.

    ``node_order`` is the stable iteration order (insertion order, as the
    reference's nodeTree/snapshot list is) — node *index* in every device
    tensor is the position in this list.
    """

    nodes: dict[str, NodeInfo] = field(default_factory=dict)
    node_order: list[str] = field(default_factory=list)
    generation: int = 0
    # per-node cache generation this snapshot last copied (owned by this
    # snapshot so several snapshots can be refreshed independently)
    node_generation: dict[str, int] = field(default_factory=dict)
    # O(Δ) refresh bookkeeping: the cache this snapshot came from, the
    # highest cache generation it has folded in, and the cache's node-set
    # epoch at that time (any add/remove invalidates the fast path)
    cache_token: object = None
    cache_watermark: int = 0
    order_epoch: int = -1
    namespaces_generation: int = -1
    # namespace name → labels (the nsLister view affinity terms match)
    namespaces: dict[str, dict[str, str]] = field(default_factory=dict)
    # object listers' view (pv/pvc/storageclass/service), copied on change only
    pvs: dict[str, "t.PersistentVolume"] = field(default_factory=dict)
    pvcs: dict[str, "t.PersistentVolumeClaim"] = field(default_factory=dict)  # "ns/name"
    storage_classes: dict[str, "t.StorageClass"] = field(default_factory=dict)
    services: dict[str, "t.Service"] = field(default_factory=dict)  # "ns/name"
    volumes_generation: int = -1
    # the Cache's DRA index, SHARED by reference (single-owner loop thread:
    # encode and Reserve both run on it, like the volume listers' dicts)
    dra: object = None
    # assigned/assumed pods carrying any (anti)affinity — lets the encoder
    # skip the whole template-group/affinity pass in O(1) on affinity-free
    # clusters (the SchedulingBasic steady state)
    pods_with_affinity: int = 0

    def node_infos(self) -> list[NodeInfo]:
        return [self.nodes[n] for n in self.node_order]

    def dirty_since(self, watermark: int) -> "list[str] | None":
        """Node names touched in the backing cache past ``watermark``
        (cache generations) — the O(Δ) candidate set the tensor encoder
        scans instead of all N nodes (the informer-to-tensor sync was an
        O(N)-python-per-cycle wall at 100k nodes). None when the snapshot
        has no live cache behind it (hand-built test snapshots): callers
        fall back to the full scan. The list may be a SUPERSET of what
        this snapshot has folded in — consumers must still gen-check each
        candidate, never trust membership alone."""
        cache = self.cache_token
        if cache is None:
            return None
        touched = getattr(cache, "touched_since", None)
        if touched is None:
            return None
        return touched(watermark)

    def appends_only_since(self, order_epoch: int) -> bool:
        """True when every node-set change in the backing cache since
        ``order_epoch`` appended to the order (no removals) — the
        precondition for the encoder's append-incremental branch (a wave
        of node ADDS extends the tensors in place instead of the full
        O(N) rebuild per event). False without a live cache."""
        cache = self.cache_token
        if cache is None:
            return False
        fn = getattr(cache, "appends_only_since", None)
        return bool(fn(order_epoch)) if fn is not None else False

    def num_nodes(self) -> int:
        return len(self.node_order)

    def all_pods(self) -> list[t.Pod]:
        return [p for n in self.node_order for p in self.nodes[n].pods.values()]


class Cache:
    """The scheduler cache (cache.go:59). Thread-safety is the caller's
    problem in this framework: the scheduling loop owns the cache and applies
    informer deltas between batch cycles (single-writer, like the reference's
    serialized scheduling cycle)."""

    def __init__(self, ttl_seconds: float = 30.0, clock=time.monotonic) -> None:
        self._nodes: dict[str, NodeInfo] = {}
        self._node_order: list[str] = []
        self._pods: dict[str, t.Pod] = {}       # uid -> pod (assigned or assumed)
        self._assumed: dict[str, float | None] = {}  # uid -> bind-finished deadline
        self._last_gen = 0
        # recency-ordered dirty-node index: node name -> generation at last
        # touch, most recent LAST — update_snapshot walks it backwards and
        # stops at the snapshot's watermark, so the per-cycle refresh is
        # O(nodes touched since last refresh), not O(all nodes)
        self._touched: "collections.OrderedDict[str, int]" = collections.OrderedDict()
        # bumped on every node add/remove (the snapshot fast path requires an
        # unchanged node set + order)
        self._order_epoch = 0
        # the order epoch at the last NON-append structural change (a node
        # removal): epochs past this are pure appends, which the encoder's
        # append-incremental branch can extend in place
        self._nonappend_epoch = 0
        self._ns_gen = 0
        self._ttl = ttl_seconds
        self._clock = clock
        self._deleted_nodes: dict[str, NodeInfo] = {}
        self._aff_pods = 0   # cached pods carrying any (anti)affinity
        self._namespaces: dict[str, dict[str, str]] = {}
        self._pvs: dict[str, t.PersistentVolume] = {}
        self._pvcs: dict[str, t.PersistentVolumeClaim] = {}
        self._storage_classes: dict[str, t.StorageClass] = {}
        self._services: dict[str, t.Service] = {}
        self._volumes_gen = 0  # object-lister generation (pv/pvc/sc/service)
        from .dra import DraIndex

        # DRA listers + pool/allocation bookkeeping (state.dra.DraIndex)
        self.dra = DraIndex()

    # --- services (the DefaultSelector feed) -----------------------------
    def add_service(self, svc: "t.Service") -> None:
        self._services[svc.key] = svc
        self._volumes_gen += 1

    update_service = add_service

    def remove_service(self, key: str) -> None:
        if self._services.pop(key, None) is not None:
            self._volumes_gen += 1

    # --- volumes (pv/pvc/storageclass listers) ---------------------------
    def add_pv(self, pv: "t.PersistentVolume") -> None:
        self._pvs[pv.name] = pv
        self._volumes_gen += 1

    update_pv = add_pv

    def remove_pv(self, name: str) -> None:
        if self._pvs.pop(name, None) is not None:
            self._volumes_gen += 1

    def add_pvc(self, pvc: "t.PersistentVolumeClaim") -> None:
        self._pvcs[pvc.key] = pvc
        self._volumes_gen += 1

    update_pvc = add_pvc

    def remove_pvc(self, key: str) -> None:
        if self._pvcs.pop(key, None) is not None:
            self._volumes_gen += 1

    def add_storage_class(self, sc: "t.StorageClass") -> None:
        self._storage_classes[sc.name] = sc
        self._volumes_gen += 1

    update_storage_class = add_storage_class

    def remove_storage_class(self, name: str) -> None:
        if self._storage_classes.pop(name, None) is not None:
            self._volumes_gen += 1

    # --- namespaces ------------------------------------------------------
    def add_namespace(self, ns: "t.Namespace") -> None:
        self._namespaces[ns.name] = ns.labels_dict()
        self._ns_gen += 1

    update_namespace = add_namespace

    def remove_namespace(self, name: str) -> None:
        if self._namespaces.pop(name, None) is not None:
            self._ns_gen += 1

    # --- generations -----------------------------------------------------
    def _next_gen(self) -> int:
        self._last_gen += 1
        return self._last_gen

    def _touch(self, info: NodeInfo) -> None:
        """Advance the node's generation and move it to the tail of the
        recency index (the snapshot fast path's work list)."""
        info.generation = self._next_gen()
        self._touched[info.node.name] = info.generation
        self._touched.move_to_end(info.node.name)

    def touched_since(self, watermark: int) -> list[str]:
        """Node names touched past generation ``watermark``, newest first —
        a backwards walk of the recency index that stops at the watermark,
        so cost is O(Δ touched), not O(all nodes). The tensor encoder uses
        this as its dirty-row candidate set (Snapshot.dirty_since)."""
        out: list[str] = []
        for name in reversed(self._touched):
            if self._touched[name] <= watermark:
                break
            out.append(name)
        return out

    def appends_only_since(self, order_epoch: int) -> bool:
        """True when every structural node-set change since ``order_epoch``
        was an append (add_node / placeholder insert) — no removal reindexed
        the order (Snapshot.appends_only_since)."""
        return self._nonappend_epoch <= order_epoch

    # --- nodes -----------------------------------------------------------
    def add_node(self, node: t.Node) -> None:
        info = self._nodes.get(node.name)
        if info is None:
            # A node deleted while its pods were still assigned keeps its
            # accounting in _deleted_nodes; a re-add (node flap) restores it.
            info = self._deleted_nodes.pop(node.name, None)
            if info is None:
                info = NodeInfo(node=node)
            self._nodes[node.name] = info
            self._node_order.append(node.name)
            self._order_epoch += 1
        info.node = node
        self._touch(info)

    def update_node(self, node: t.Node) -> None:
        self.add_node(node)

    def has_node(self, name: str) -> bool:
        return name in self._nodes

    def get_node_info(self, name: str) -> NodeInfo | None:
        """Live NodeInfo view (single-owner loop access — lifecycle plugins
        read labels without forcing a snapshot refresh)."""
        return self._nodes.get(name)

    # live lister views (satisfy the VolumeState snapshot-like protocol)
    @property
    def pvs(self) -> dict:
        return self._pvs

    @property
    def pvcs(self) -> dict:
        return self._pvcs

    @property
    def storage_classes(self) -> dict:
        return self._storage_classes

    def remove_node(self, name: str) -> None:
        """cache.go RemoveNode semantics: the NodeInfo must survive while pods
        are still assigned to it (pod deletes arrive on a different watch);
        it is kept out of the snapshot but retains its accounting until the
        last pod drains."""
        info = self._nodes.pop(name, None)
        if info is None:
            return
        self._node_order.remove(name)
        self._order_epoch += 1
        self._nonappend_epoch = self._order_epoch   # removal reindexes order
        self._touched.pop(name, None)
        if info.pods:
            self._deleted_nodes[name] = info

    # --- pods ------------------------------------------------------------
    def add_pod(self, pod: t.Pod) -> None:
        """An assigned pod observed from the watch (AddPod). Idempotent: a
        relisted duplicate Add replaces the previous accounting instead of
        double-counting (the reference cache errors on duplicate adds;
        replace-on-add keeps aggregates correct under informer resyncs)."""
        if pod.uid in self._pods:
            # Confirmation of an assumed pod, or a duplicate/resynced Add:
            # replace the previous view.
            self._remove_pod_internal(self._pods[pod.uid])
            self._assumed.pop(pod.uid, None)
        self._add_pod_internal(pod)

    def update_pod(self, old: t.Pod, new: t.Pod) -> None:
        """The cached state, not the caller's ``old``, is what gets removed
        (cache.go:560 UpdatePod uses currState) — informer deltas can carry a
        stale view whose node/requests diverge from what we accounted."""
        cached = self._pods.get(old.uid, old)
        self._remove_pod_internal(cached)
        self._add_pod_internal(new)

    def remove_pod(self, pod: t.Pod) -> None:
        """cache.go:583 RemovePod: remove the CACHED pod — a Delete event may
        arrive with node_name unset (bind never observed) and must still drop
        the accounting from whichever node we assumed it onto."""
        self._assumed.pop(pod.uid, None)
        cached = self._pods.get(pod.uid, pod)
        self._remove_pod_internal(cached)

    def assume_pod(self, pod: t.Pod) -> None:
        """cache.go:397 AssumePod — pod must carry node_name."""
        if not pod.node_name:
            raise ValueError("assumed pod must have node_name set")
        if pod.uid in self._pods:
            raise KeyError(f"pod {pod.uid} already in cache")
        self._add_pod_internal(pod)
        self._assumed[pod.uid] = None  # no expiry until binding finishes

    def finish_binding(self, uid: str) -> None:
        if uid in self._assumed:
            self._assumed[uid] = self._clock() + self._ttl

    def forget_pod(self, pod: t.Pod) -> None:
        if pod.uid in self._assumed:
            del self._assumed[pod.uid]
            self._remove_pod_internal(pod)

    def has_pod(self, uid: str) -> bool:
        """Is the pod (assigned or assumed) still present? Preemption's
        eligibility gate polls this: a victim whose informer delete hasn't
        arrived is 'terminating' (default_preemption.go:364)."""
        return uid in self._pods

    def is_assumed(self, uid: str) -> bool:
        return uid in self._assumed

    def cleanup_expired(self) -> list[str]:
        """Expire assumed pods whose bind never confirmed (cache.go expiry
        goroutine). Returns expired uids."""
        now = self._clock()
        expired = [
            uid for uid, dl in self._assumed.items() if dl is not None and dl < now
        ]
        for uid in expired:
            pod = self._pods[uid]
            del self._assumed[uid]
            self._remove_pod_internal(pod)
        return expired

    def _add_pod_internal(self, pod: t.Pod) -> None:
        if not pod.node_name:
            raise ValueError(f"cached pod {pod.uid} must have node_name set")
        if _pod_has_affinity(pod):
            self._aff_pods += 1
        self._pods[pod.uid] = pod
        info = self._nodes.get(pod.node_name)
        if info is None and pod.node_name in self._deleted_nodes:
            info = self._deleted_nodes[pod.node_name]
        if info is None:
            # Pod on an unknown node: create a placeholder (the reference
            # keeps such pods in an imaginary nodeInfo too).
            info = NodeInfo(node=t.Node(name=pod.node_name))
            self._nodes[pod.node_name] = info
            self._node_order.append(pod.node_name)
            self._order_epoch += 1
        info.add_pod(pod)
        self._touch(info)

    def _remove_pod_internal(self, pod: t.Pod) -> None:
        known = self._pods.pop(pod.uid, None)
        if known is not None and _pod_has_affinity(known):
            self._aff_pods -= 1
        info = self._nodes.get(pod.node_name)
        if info is None:
            info = self._deleted_nodes.get(pod.node_name)
        if info is not None:
            info.remove_pod(pod)
            self._touch(info)
            if not info.pods and pod.node_name in self._deleted_nodes:
                del self._deleted_nodes[pod.node_name]

    # --- snapshot --------------------------------------------------------
    def update_snapshot(self, snapshot: Snapshot | None = None) -> Snapshot:
        """Incremental snapshot refresh (cache.go:190): clone only nodes whose
        generation moved; preserve node order.

        Fast path: a snapshot previously refreshed from THIS cache whose node
        set/order hasn't changed walks the recency index backwards from the
        newest touch down to its watermark — O(nodes touched since the last
        refresh). Any node add/remove (or a foreign snapshot) falls back to
        the full O(N) scan."""
        if snapshot is None:
            snapshot = Snapshot()
        if (
            snapshot.cache_token is self
            and snapshot.order_epoch == self._order_epoch
        ):
            # O(Δ): only nodes touched past the watermark need a re-clone
            for name in reversed(self._touched):
                gen = self._touched[name]
                if gen <= snapshot.cache_watermark:
                    break
                info = self._nodes.get(name)
                if info is None:
                    continue  # deleted-node accounting (not snapshotted)
                snapshot.nodes[name] = info.clone()
                snapshot.node_generation[name] = info.generation
        else:
            new_nodes: dict[str, NodeInfo] = {}
            new_gens: dict[str, int] = {}
            for name in self._node_order:
                info = self._nodes[name]
                prev = snapshot.nodes.get(name)
                if prev is not None and snapshot.node_generation.get(name) == info.generation:
                    new_nodes[name] = prev
                else:
                    new_nodes[name] = info.clone()
                new_gens[name] = info.generation
            snapshot.nodes = new_nodes
            snapshot.node_generation = new_gens
            snapshot.node_order = list(self._node_order)
            snapshot.cache_token = self
            snapshot.order_epoch = self._order_epoch
        snapshot.cache_watermark = self._last_gen
        if snapshot.namespaces_generation != self._ns_gen:
            # namespace labels are read-only per object: copy per CHANGE,
            # not per refresh (the per-cycle dict rebuild was hot-loop waste)
            snapshot.namespaces = {
                k: dict(v) for k, v in self._namespaces.items()
            }
            snapshot.namespaces_generation = self._ns_gen
        if snapshot.volumes_generation != self._volumes_gen:
            # lister objects are immutable values: a shallow dict copy per
            # CHANGE (not per refresh) gives the snapshot a stable view
            snapshot.pvs = dict(self._pvs)
            snapshot.pvcs = dict(self._pvcs)
            snapshot.storage_classes = dict(self._storage_classes)
            snapshot.services = dict(self._services)
            snapshot.volumes_generation = self._volumes_gen
        snapshot.dra = self.dra
        snapshot.pods_with_affinity = self._aff_pods
        snapshot.generation = self._next_gen()
        return snapshot
