"""String interning tables.

Every string the kernels care about (label keys, label values, topology keys,
namespaces, image names, port triples, selector signatures) is interned to a
dense int id on the host so that device tensors contain only integers. This
replaces the reference's pervasive map[string]string comparisons with integer
gathers — the TPU never sees a string.
"""

from __future__ import annotations

from typing import Hashable, Iterable


class Vocab:
    """Monotonic string→id intern table (ids are stable across updates)."""

    __slots__ = ("_to_id", "_to_str")

    def __init__(self) -> None:
        self._to_id: dict[Hashable, int] = {}
        self._to_str: list[Hashable] = []

    def intern(self, s: Hashable) -> int:
        i = self._to_id.get(s)
        if i is None:
            i = len(self._to_str)
            self._to_id[s] = i
            self._to_str.append(s)
        return i

    def get(self, s: Hashable, default: int = -1) -> int:
        return self._to_id.get(s, default)

    def lookup(self, i: int) -> Hashable:
        return self._to_str[i]

    def intern_all(self, items: Iterable[Hashable]) -> list[int]:
        return [self.intern(s) for s in items]

    def __len__(self) -> int:
        return len(self._to_str)

    def __contains__(self, s: Hashable) -> bool:
        return s in self._to_id
