"""Node-topology coordinate tensors (ROADMAP item 3).

Racks and TPU slices arrive as ordinary node labels; the scoring stack
wants them as SMALL DENSE integers so a gang's slice concentration and a
slice's occupancy are single segment-sums over the node axis — zero
per-pod Python at score time. ``topology_tensors`` reads the encoder's
interned label matrix (``NodeTensors._ensure_label_matrix``), picks the
well-known slice/rack columns, and remaps each to a dense coordinate in
``[0, D)`` with ``D`` itself standing for "no label". The result is
memoized on the NodeTensors object and rides ``encode_snapshot``'s
in-place growth: ``_refresh_tensors`` drops the memo whenever a node
object was replaced or appended (labels may have changed), and every
other cycle reuses the cached coordinates for free.

Arrays are allocated at the PADDED node capacity like every other node
table, so the device block shards under ``parallel.mesh`` without a
resize; rows past ``num_nodes`` read as unlabeled (the ``D`` bucket),
which scores exactly like a node outside every slice.
"""
from __future__ import annotations

import dataclasses

import numpy as np

# Well-known topology label keys. The slice key mirrors the GKE TPU
# placement convention; the rack key is the standard topology prefix.
# Trace generation (perf.workloads) and the tests stamp these same keys,
# so the whole stack shares one label grammar.
SLICE_KEY = "kubetpu.io/tpu-slice"
RACK_KEY = "topology.kubernetes.io/rack"


@dataclasses.dataclass(frozen=True)
class TopologyTensors:
    """Host-side dense topology coordinates at padded node capacity."""

    slice_id: np.ndarray        # (cap,) int32 in [0, num_slices]; == num_slices ⇒ unlabeled
    rack_id: np.ndarray         # (cap,) int32 in [0, num_racks]; == num_racks ⇒ unlabeled
    num_slices: int
    num_racks: int
    slice_names: tuple          # dense slice id → label value (explain rendering)
    rack_names: tuple

    @property
    def labeled(self) -> bool:
        """True when ANY node carries a slice or rack label — the signal
        ``--topology auto`` keys off (an unlabeled cluster stays on the
        bit-identical topology-off path)."""
        return self.num_slices > 0 or self.num_racks > 0


def _dense_column(nt, key: str) -> "tuple[np.ndarray, int, tuple]":
    """Remap one label column to dense ids. Returns ``(ids, D, names)``
    where unlabeled rows (and padded capacity past ``num_nodes``) carry
    ``D``. Dense ids follow val-vocab intern order, so they are stable
    across incremental refreshes that don't touch labels."""
    cap = nt.alloc.shape[0]
    n = nt.num_nodes
    kid = nt.key_vocab.get(key)
    if kid < 0:
        return np.zeros(cap, dtype=np.int32), 0, ()
    col = np.full(cap, -1, dtype=np.int32)
    col[:n] = nt._ensure_label_matrix()[:n, kid]
    present = np.unique(col[col >= 0])
    d = int(present.size)
    if d == 0:
        return np.zeros(cap, dtype=np.int32), 0, ()
    # labeled values are a subset of ``present`` so searchsorted is exact
    idx = np.searchsorted(present, np.clip(col, 0, None))
    ids = np.where(col >= 0, idx, d).astype(np.int32)
    names = tuple(nt.val_vocab.lookup(int(v)) for v in present)
    return ids, d, names


def topology_tensors(nt) -> TopologyTensors:
    """Dense coordinates for ``nt``, memoized until the node set or any
    node object changes (``_refresh_tensors`` clears the memo)."""
    memo = getattr(nt, "topo_memo", None)
    if (
        isinstance(memo, TopologyTensors)
        and memo.slice_id.shape[0] == nt.alloc.shape[0]
    ):
        return memo
    slice_id, n_slices, slice_names = _dense_column(nt, SLICE_KEY)
    rack_id, n_racks, rack_names = _dense_column(nt, RACK_KEY)
    tt = TopologyTensors(
        slice_id=slice_id,
        rack_id=rack_id,
        num_slices=n_slices,
        num_racks=n_racks,
        slice_names=slice_names,
        rack_names=rack_names,
    )
    nt.topo_memo = tt
    return tt
