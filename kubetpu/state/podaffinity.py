"""InterPodAffinity tensorization.

Reference: pkg/scheduler/framework/plugins/interpodaffinity/
- filtering.go:44 preFilterState — three topology-pair count maps:
  affinityCounts (existing pods matching ALL of the incoming pod's required
  affinity terms), antiAffinityCounts (existing pods matching ANY incoming
  required anti-affinity term, per term), existingAntiAffinityCounts
  (existing pods whose own required anti-affinity terms match the incoming
  pod); Filter checks at :364-419.
- scoring.go:81 processExistingPod — topologyScore contributions from the
  incoming pod's preferred terms, existing pods' required-affinity terms
  (× HardPodAffinityWeight), and existing pods' preferred terms; NormalizeScore
  :258 is min-max over filtered nodes.

Tensorization: every distinct *count row* is interned. A row is a (term,
grouping) pair whose per-topology-value counts the reference keeps in a Go
map; here each row carries:

- ``node_domain (N,)``: interned id of each node's value for the row's
  topology key (−1 when absent),
- ``base_sums (D,)``: per-domain counts from existing (assigned) pods,
- an update column in ``update (P, R)``: how much an in-batch assignment of
  pending pod p adds to the row on the chosen node's domain
  (preFilterState.updateWithPod / AddPod semantics, filtering.go:75).

Row kinds:
- FA (incoming required affinity, one row per (term-set, term)): counts pods
  matching ALL terms of the set; Filter needs every FA row of the pod > 0 at
  the node's domain, with the self-affinity escape (filtering.go:414).
- RA (incoming required anti-affinity, one row per term): node infeasible if
  count > 0 at its domain.
- EA (required anti-affinity terms of existing/assignable pods, one row per
  distinct term): node infeasible for pod p if the term matches p
  (``ea_match (P, R)``) and count > 0 at the node's domain.
- SC (scoring): one row per distinct (term, weight-source); ``score_w (P, R)``
  carries the signed weight each pending pod contributes/receives
  (+w incoming preferred affinity, −w incoming preferred anti-affinity,
  +HardPodAffinityWeight × existing required-affinity match, ±w existing
  preferred terms).

Namespace semantics: a term's namespaces default to the owner pod's namespace
(framework.NewPodInfo defaultNamespaces); a non-nil namespace_selector is
evaluated against the target pod's NAMESPACE labels (AffinityTerm.Matches,
framework/types.go — nsLabels come from the nsLister snapshot,
GetNamespaceLabelsSnapshot). ``encode_pod_affinity`` takes the snapshot's
namespace→labels map; a namespace absent from the map matches as if it had
no labels (empty selector matches, non-empty doesn't), which is also the
reference behavior for an unsynced namespace.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from ..api import selectors as sel
from ..api import types as t
from .encoder import NodeTensors
from .vocab import Vocab


def term_matches_pod(
    term: t.PodAffinityTerm,
    owner_ns: str,
    pod: t.Pod,
    ns_labels: "dict[str, str] | None" = None,
) -> bool:
    """AffinityTerm.Matches (framework/types.go): namespace membership OR
    namespace-selector match (against the labels of the TARGET pod's
    namespace), AND label selector match."""
    namespaces = term.namespaces or (owner_ns,)
    ns_ok = pod.namespace in namespaces
    if not ns_ok and term.namespace_selector is not None:
        ns_ok = sel.label_selector_matches(term.namespace_selector, ns_labels or {})
    if not ns_ok:
        return False
    if term.selector is None:
        return False
    return sel.label_selector_matches(term.selector, pod.labels_dict())


def _req_affinity_terms(pod: t.Pod) -> tuple[t.PodAffinityTerm, ...]:
    a = pod.affinity.pod_affinity if pod.affinity else None
    return a.required if a else ()


def _req_anti_terms(pod: t.Pod) -> tuple[t.PodAffinityTerm, ...]:
    a = pod.affinity.pod_anti_affinity if pod.affinity else None
    return a.required if a else ()


def _pref_affinity_terms(pod: t.Pod) -> tuple[t.WeightedPodAffinityTerm, ...]:
    a = pod.affinity.pod_affinity if pod.affinity else None
    return a.preferred if a else ()


def _pref_anti_terms(pod: t.Pod) -> tuple[t.WeightedPodAffinityTerm, ...]:
    a = pod.affinity.pod_anti_affinity if pod.affinity else None
    return a.preferred if a else ()


def has_any_affinity(pod: t.Pod) -> bool:
    a = pod.affinity
    if a is None:
        return False
    pa, paa = a.pod_affinity, a.pod_anti_affinity
    return bool(
        (pa is not None and (pa.required or pa.preferred))
        or (paa is not None and (paa.required or paa.preferred))
    )


@dataclass
class PodAffinityTensors:
    """Numpy-side encoding; None from the encoder when nothing to do."""

    # rows
    node_domain: np.ndarray   # (R, N) int32, -1 = key absent
    has_key: np.ndarray       # (R, N) bool
    base_sums: np.ndarray     # (R, D) int64
    update: np.ndarray        # (P, R) int64 — increment when pod p is assigned
    # filtering — per-pod row-id slots (−1 unused) so kernels touch only the
    # rows a pod actually uses, not all R (the dense (R, N) gather per scan
    # step was the dominant cost at 5k nodes)
    fa_rows: np.ndarray       # (P, CA) int32 row id, -1 unused
    fa_self: np.ndarray       # (P,) bool — pod matches all its own aff terms
    ra_rows: np.ndarray       # (P, CR) int32 row id, -1 unused
    ea_rows: np.ndarray       # (P, CE) int32 — EA rows whose term matches pod p
    # scoring — slots + signed weights
    score_rows: np.ndarray    # (P, CS) int32
    score_vals: np.ndarray    # (P, CS) int64
    has_filter_work: bool
    has_score_work: bool

    @property
    def num_rows(self) -> int:
        return self.node_domain.shape[0]

    @property
    def max_domains(self) -> int:
        return self.base_sums.shape[1]


def encode_pod_affinity(
    nt: NodeTensors,
    pods: Sequence[t.Pod],
    hard_pod_affinity_weight: int = 1,
    pad_pods: int | None = None,
    namespaces: "dict[str, dict[str, str]] | None" = None,
) -> PodAffinityTensors | None:
    """Build affinity tensors; None when neither pending pods nor existing
    pods carry any (anti)affinity. ``namespaces`` is the snapshot's
    namespace→labels map, matched by namespace selectors."""
    ns_map = namespaces or {}

    def ns_labels_of(q: t.Pod) -> dict[str, str]:
        return ns_map.get(q.namespace, {})

    P = len(pods)
    N = nt.num_nodes
    NC = nt.alloc.shape[0]
    PP = max(pad_pods or P, P)

    existing: list[tuple[t.Pod, int]] = []       # (pod, node index)
    for n_i, info in enumerate(nt.infos):
        for ex in info.pods.values():
            existing.append((ex, n_i))
    any_existing_aff = any(has_any_affinity(ex) for ex, _ in existing)
    any_pending_aff = any(has_any_affinity(p) for p in pods)
    if not any_existing_aff and not any_pending_aff:
        return None

    row_vocab = Vocab()
    row_meta: list[dict] = []

    def row(kind: str, key: str, match_fn_sig, meta) -> int:
        rid = row_vocab.intern((kind, key, match_fn_sig))
        if rid == len(row_meta):
            row_meta.append(dict(kind=kind, key=key, **meta))
        return rid

    # ---- collect rows ----------------------------------------------------
    fa_slots: list[list[int]] = [[] for _ in range(P)]
    ra_slots: list[list[int]] = [[] for _ in range(P)]
    fa_self = np.zeros(PP, dtype=bool)

    for i, p in enumerate(pods):
        aff = _req_affinity_terms(p)
        if aff:
            set_sig = (tuple(aff), p.namespace)
            for term in aff:
                rid = row(
                    "FA", term.topology_key, ("set", set_sig),
                    dict(terms=aff, ns=p.namespace),
                )
                fa_slots[i].append(rid)
            fa_self[i] = all(
                term_matches_pod(tm, p.namespace, p, ns_labels_of(p))
                for tm in aff
            )
        for term in _req_anti_terms(p):
            rid = row(
                "RA", term.topology_key, ("term", term, p.namespace),
                dict(term=term, ns=p.namespace),
            )
            ra_slots[i].append(rid)
        for wt in _pref_affinity_terms(p):
            row(
                "SCI", wt.term.topology_key,
                ("pref", wt.term, p.namespace),
                dict(term=wt.term, ns=p.namespace),
            )
        for wt in _pref_anti_terms(p):
            row(
                "SCI", wt.term.topology_key,
                ("pref", wt.term, p.namespace),
                dict(term=wt.term, ns=p.namespace),
            )

    # rows driven by existing/assignable pods' own terms. Pending pods also
    # contribute rows here: once assigned in-batch they become "existing" for
    # later pods.
    def existing_rows(pod: t.Pod) -> list[tuple[int, int]]:
        """Rows this pod's own terms maintain, with the per-assignment
        increment (1 for counts; weight is applied at score time via
        score_w, so SC rows also increment by their weight here)."""
        out: list[tuple[int, int]] = []
        for term in _req_anti_terms(pod):
            rid = row(
                "EA", term.topology_key, ("eterm", term, pod.namespace),
                dict(term=term, ns=pod.namespace),
            )
            out.append((rid, 1))
        for term in _req_affinity_terms(pod):
            rid = row(
                "SCH", term.topology_key, ("hterm", term, pod.namespace),
                dict(term=term, ns=pod.namespace),
            )
            out.append((rid, 1))
        for wt in _pref_affinity_terms(pod):
            rid = row(
                "SCP", wt.term.topology_key,
                ("pterm", wt.term, pod.namespace, wt.weight, 1),
                dict(term=wt.term, ns=pod.namespace, weight=wt.weight, sign=1),
            )
            out.append((rid, 1))
        for wt in _pref_anti_terms(pod):
            rid = row(
                "SCP", wt.term.topology_key,
                ("pterm", wt.term, pod.namespace, wt.weight, -1),
                dict(term=wt.term, ns=pod.namespace, weight=wt.weight, sign=-1),
            )
            out.append((rid, 1))
        return out

    ex_rows: list[list[tuple[int, int]]] = [existing_rows(ex) for ex, _ in existing]
    pend_rows: list[list[tuple[int, int]]] = [existing_rows(p) for p in pods]

    R = len(row_meta)
    if R == 0:
        return None

    # ---- per-row node domains + base sums --------------------------------
    key_domains: dict[str, tuple[np.ndarray, Vocab]] = {}

    def domains_for(key: str) -> tuple[np.ndarray, Vocab]:
        got = key_domains.get(key)
        if got is None:
            vals = nt.topology_values(key)          # (N,) interned label ids
            dv = Vocab()
            dom = np.full(N, -1, dtype=np.int32)
            for n_i in range(N):
                if vals[n_i] >= 0:
                    dom[n_i] = dv.intern(int(vals[n_i]))
            got = (dom, dv)
            key_domains[key] = got
        return got

    row_domains = [domains_for(m["key"]) for m in row_meta]
    D = max((len(dv) for _, dv in row_domains), default=1) or 1

    node_domain = np.full((R, NC), -1, dtype=np.int32)
    has_key = np.zeros((R, NC), dtype=bool)
    base_sums = np.zeros((R, D), dtype=np.int64)
    for r, (dom, _dv) in enumerate(row_domains):
        node_domain[r, :N] = dom
        has_key[r, :N] = dom >= 0

    # does pod q "drive" row r's count (as an existing/assigned pod)?
    def count_match(meta: dict, q: t.Pod) -> bool:
        kind = meta["kind"]
        if kind == "FA":
            return all(
                term_matches_pod(tm, meta["ns"], q, ns_labels_of(q))
                for tm in meta["terms"]
            )
        if kind in ("RA", "SCI"):
            return term_matches_pod(meta["term"], meta["ns"], q, ns_labels_of(q))
        # EA/SCH/SCP rows count pods that HAVE the term — membership was
        # resolved when the row was appended for that pod, so here we only
        # get called for base sums via ex_rows/pend_rows, not a predicate.
        raise AssertionError("count_match only for FA/RA/SCI rows")

    match_cache: dict[tuple, bool] = {}

    def cached_count_match(r: int, q: t.Pod) -> bool:
        key = (r, q.labels, q.namespace)
        got = match_cache.get(key)
        if got is None:
            got = count_match(row_meta[r], q)
            match_cache[key] = got
        return got

    for (ex, n_i), rows_of_ex in zip(existing, ex_rows):
        # rows where the existing pod is the TARGET (incoming pod's terms)
        for r, meta in enumerate(row_meta):
            if meta["kind"] in ("FA", "RA", "SCI"):
                d = node_domain[r, n_i]
                if d >= 0 and cached_count_match(r, ex):
                    base_sums[r, d] += 1
        # rows where the existing pod is the SOURCE (its own terms)
        for r, inc in rows_of_ex:
            d = node_domain[r, n_i]
            if d >= 0:
                base_sums[r, d] += inc

    # ---- update matrix (in-batch assignment increments) ------------------
    update = np.zeros((PP, R), dtype=np.int64)
    for i, p in enumerate(pods):
        for r, meta in enumerate(row_meta):
            if meta["kind"] in ("FA", "RA", "SCI") and cached_count_match(r, p):
                update[i, r] += 1
        for r, inc in pend_rows[i]:
            update[i, r] += inc

    # ---- filtering tensors ----------------------------------------------
    CA = max((len(s) for s in fa_slots), default=1) or 1
    CR = max((len(s) for s in ra_slots), default=1) or 1
    fa_rows = np.full((PP, CA), -1, dtype=np.int32)
    ra_rows = np.full((PP, CR), -1, dtype=np.int32)
    for i in range(P):
        for c, rid in enumerate(fa_slots[i]):
            fa_rows[i, c] = rid
        for c, rid in enumerate(ra_slots[i]):
            ra_rows[i, c] = rid

    ea_lists: list[list[int]] = []
    for i, p in enumerate(pods):
        lst = [
            r for r, meta in enumerate(row_meta)
            if meta["kind"] == "EA"
            and term_matches_pod(meta["term"], meta["ns"], p, ns_labels_of(p))
        ]
        ea_lists.append(lst)
    CE = max((len(x) for x in ea_lists), default=1) or 1
    ea_rows = np.full((PP, CE), -1, dtype=np.int32)
    for i, lst in enumerate(ea_lists):
        ea_rows[i, : len(lst)] = lst

    # ---- scoring slots ---------------------------------------------------
    sc_lists: list[list[tuple[int, int]]] = []
    for i, p in enumerate(pods):
        w: dict[int, int] = {}
        # incoming preferred terms: row counts matching existing pods; the
        # pod's own weight applies (scoring.go:98/:105)
        for wt in _pref_affinity_terms(p):
            rid = row_vocab.get(("SCI", wt.term.topology_key, ("pref", wt.term, p.namespace)))
            if rid >= 0:
                w[rid] = w.get(rid, 0) + wt.weight
        for wt in _pref_anti_terms(p):
            rid = row_vocab.get(("SCI", wt.term.topology_key, ("pref", wt.term, p.namespace)))
            if rid >= 0:
                w[rid] = w.get(rid, 0) - wt.weight
        # existing pods' terms vs this pod (scoring.go:110-124)
        for r, meta in enumerate(row_meta):
            if meta["kind"] == "SCH" and hard_pod_affinity_weight > 0:
                if term_matches_pod(meta["term"], meta["ns"], p, ns_labels_of(p)):
                    w[r] = w.get(r, 0) + hard_pod_affinity_weight
            elif meta["kind"] == "SCP":
                if term_matches_pod(meta["term"], meta["ns"], p, ns_labels_of(p)):
                    w[r] = w.get(r, 0) + meta["sign"] * meta["weight"]
        sc_lists.append(sorted(w.items()))
    CS = max((len(x) for x in sc_lists), default=1) or 1
    score_rows = np.full((PP, CS), -1, dtype=np.int32)
    score_vals = np.zeros((PP, CS), dtype=np.int64)
    for i, lst in enumerate(sc_lists):
        for c, (rid, val) in enumerate(lst):
            score_rows[i, c] = rid
            score_vals[i, c] = val

    has_filter_work = bool(
        (fa_rows >= 0).any() or (ra_rows >= 0).any() or (ea_rows >= 0).any()
    )
    has_score_work = bool((score_rows >= 0).any())

    return PodAffinityTensors(
        node_domain=node_domain,
        has_key=has_key,
        base_sums=base_sums,
        update=update,
        fa_rows=fa_rows,
        fa_self=fa_self,
        ra_rows=ra_rows,
        ea_rows=ea_rows,
        score_rows=score_rows,
        score_vals=score_vals,
        has_filter_work=has_filter_work,
        has_score_work=has_score_work,
    )
