"""InterPodAffinity tensorization.

Reference: pkg/scheduler/framework/plugins/interpodaffinity/
- filtering.go:44 preFilterState — three topology-pair count maps:
  affinityCounts (existing pods matching ALL of the incoming pod's required
  affinity terms), antiAffinityCounts (existing pods matching ANY incoming
  required anti-affinity term, per term), existingAntiAffinityCounts
  (existing pods whose own required anti-affinity terms match the incoming
  pod); Filter checks at :364-419.
- scoring.go:81 processExistingPod — topologyScore contributions from the
  incoming pod's preferred terms, existing pods' required-affinity terms
  (× HardPodAffinityWeight), and existing pods' preferred terms; NormalizeScore
  :258 is min-max over filtered nodes.

Tensorization: every distinct *count row* is interned. A row is a (term,
grouping) pair whose per-topology-value counts the reference keeps in a Go
map; here each row carries:

- ``node_domain (N,)``: interned id of each node's value for the row's
  topology key (−1 when absent),
- ``base_sums (D,)``: per-domain counts from existing (assigned) pods,
- an update column in ``update (P, R)``: how much an in-batch assignment of
  pending pod p adds to the row on the chosen node's domain
  (preFilterState.updateWithPod / AddPod semantics, filtering.go:75).

Row kinds:
- FA (incoming required affinity, one row per (term-set, term)): counts pods
  matching ALL terms of the set; Filter needs every FA row of the pod > 0 at
  the node's domain, with the self-affinity escape (filtering.go:414).
- RA (incoming required anti-affinity, one row per term): node infeasible if
  count > 0 at its domain.
- EA (required anti-affinity terms of existing/assignable pods, one row per
  distinct term): node infeasible for pod p if the term matches p
  (``ea_match (P, R)``) and count > 0 at the node's domain.
- SC (scoring): one row per distinct (term, weight-source); ``score_w (P, R)``
  carries the signed weight each pending pod contributes/receives
  (+w incoming preferred affinity, −w incoming preferred anti-affinity,
  +HardPodAffinityWeight × existing required-affinity match, ±w existing
  preferred terms).

Namespace semantics: a term's namespaces default to the owner pod's namespace
(framework.NewPodInfo defaultNamespaces); a non-nil namespace_selector is
evaluated against the target pod's NAMESPACE labels (AffinityTerm.Matches,
framework/types.go — nsLabels come from the nsLister snapshot,
GetNamespaceLabelsSnapshot). ``encode_pod_affinity`` takes the snapshot's
namespace→labels map; a namespace absent from the map matches as if it had
no labels (empty selector matches, non-empty doesn't), which is also the
reference behavior for an unsynced namespace.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from ..api import selectors as sel
from ..api import types as t
from .encoder import NodeTensors
from .vocab import Vocab


def term_matches(
    term: t.PodAffinityTerm,
    owner_ns: str,
    target_ns: str,
    target_labels: dict,
    ns_labels: "dict[str, str] | None" = None,
) -> bool:
    """AffinityTerm.Matches (framework/types.go) against a (labels,
    namespace) TEMPLATE rather than a pod object: namespace membership OR
    namespace-selector match (against the labels of the target's
    namespace), AND label selector match. Pods stamped from one controller
    template share (labels, namespace), so match verdicts are per-template
    facts — the encode cache memoizes them across cycles."""
    namespaces = term.namespaces or (owner_ns,)
    ns_ok = target_ns in namespaces
    if not ns_ok and term.namespace_selector is not None:
        ns_ok = sel.label_selector_matches(term.namespace_selector, ns_labels or {})
    if not ns_ok:
        return False
    if term.selector is None:
        return False
    return sel.label_selector_matches(term.selector, target_labels)


def term_matches_pod(
    term: t.PodAffinityTerm,
    owner_ns: str,
    pod: t.Pod,
    ns_labels: "dict[str, str] | None" = None,
) -> bool:
    return term_matches(term, owner_ns, pod.namespace, pod.labels_dict(), ns_labels)


def _req_affinity_terms(pod: t.Pod) -> tuple[t.PodAffinityTerm, ...]:
    a = pod.affinity.pod_affinity if pod.affinity else None
    return a.required if a else ()


def _req_anti_terms(pod: t.Pod) -> tuple[t.PodAffinityTerm, ...]:
    a = pod.affinity.pod_anti_affinity if pod.affinity else None
    return a.required if a else ()


def _pref_affinity_terms(pod: t.Pod) -> tuple[t.WeightedPodAffinityTerm, ...]:
    a = pod.affinity.pod_affinity if pod.affinity else None
    return a.preferred if a else ()


def _pref_anti_terms(pod: t.Pod) -> tuple[t.WeightedPodAffinityTerm, ...]:
    a = pod.affinity.pod_anti_affinity if pod.affinity else None
    return a.preferred if a else ()


def affinity_has_terms(a: "t.Affinity | None") -> bool:
    if a is None:
        return False
    pa, paa = a.pod_affinity, a.pod_anti_affinity
    return bool(
        (pa is not None and (pa.required or pa.preferred))
        or (paa is not None and (paa.required or paa.preferred))
    )


def has_any_affinity(pod: t.Pod) -> bool:
    return affinity_has_terms(pod.affinity)


def source_row_specs(aff: "t.Affinity | None", ns: str) -> tuple:
    """The rows a pod shaped ``(affinity, namespace)`` maintains as an
    existing/assigned pod, as ``(vocab_key, meta, inc)`` specs: EA (its
    required anti-affinity terms), SCH (its required affinity terms,
    scored × HardPodAffinityWeight), SCP (its preferred terms, signed).
    A pure function of the TEMPLATE — the encode cache memoizes it, so a
    1000-pod deployment contributes one spec computation, not 1000
    per-pod ``existing_rows`` walks per cycle."""
    pa = aff.pod_affinity if aff else None
    paa = aff.pod_anti_affinity if aff else None
    out: list[tuple] = []
    for term in (paa.required if paa else ()):
        out.append((
            ("EA", term.topology_key, ("eterm", term, ns)),
            dict(term=term, ns=ns), 1,
        ))
    for term in (pa.required if pa else ()):
        out.append((
            ("SCH", term.topology_key, ("hterm", term, ns)),
            dict(term=term, ns=ns), 1,
        ))
    for wt in (pa.preferred if pa else ()):
        out.append((
            ("SCP", wt.term.topology_key, ("pterm", wt.term, ns, wt.weight, 1)),
            dict(term=wt.term, ns=ns, weight=wt.weight, sign=1), 1,
        ))
    for wt in (paa.preferred if paa else ()):
        out.append((
            ("SCP", wt.term.topology_key, ("pterm", wt.term, ns, wt.weight, -1)),
            dict(term=wt.term, ns=ns, weight=wt.weight, sign=-1), 1,
        ))
    return tuple(out)


@dataclass
class PodAffinityTensors:
    """Numpy-side encoding; None from the encoder when nothing to do."""

    # rows
    node_domain: np.ndarray   # (R, N) int32, -1 = key absent
    has_key: np.ndarray       # (R, N) bool
    base_sums: np.ndarray     # (R, D) int64
    update: np.ndarray        # (P, R) int64 — increment when pod p is assigned
    # filtering — per-pod row-id slots (−1 unused) so kernels touch only the
    # rows a pod actually uses, not all R (the dense (R, N) gather per scan
    # step was the dominant cost at 5k nodes)
    fa_rows: np.ndarray       # (P, CA) int32 row id, -1 unused
    fa_self: np.ndarray       # (P,) bool — pod matches all its own aff terms
    ra_rows: np.ndarray       # (P, CR) int32 row id, -1 unused
    ea_rows: np.ndarray       # (P, CE) int32 — EA rows whose term matches pod p
    # scoring — slots + signed weights
    score_rows: np.ndarray    # (P, CS) int32
    score_vals: np.ndarray    # (P, CS) int64
    has_filter_work: bool
    has_score_work: bool

    @property
    def num_rows(self) -> int:
        return self.node_domain.shape[0]

    @property
    def max_domains(self) -> int:
        return self.base_sums.shape[1]


def encode_pod_affinity(
    nt: NodeTensors,
    pods: Sequence[t.Pod],
    hard_pod_affinity_weight: int = 1,
    pad_pods: int | None = None,
    namespaces: "dict[str, dict[str, str]] | None" = None,
    cache=None,
    groups: dict | None = None,
) -> PodAffinityTensors | None:
    """Build affinity tensors; None when neither pending pods nor existing
    pods carry any (anti)affinity. ``namespaces`` is the snapshot's
    namespace→labels map, matched by namespace selectors.

    ``groups``: precomputed template groups
    (``encode_cache.collect_pod_groups``) — ``{template_key(pod):
    (N,) counts}`` with key[0:3] = (labels, ns, affinity); None builds
    them here. The base-sum accumulation is
    per (row × template) numpy segment sums over these count vectors, not
    per (row × existing pod) Python — the r05 fullstack trace's dominant
    encode cost. ``cache``: an ``encode_cache.EncodeCache`` whose
    persistent term-spec and match-verdict stores carry the per-template
    facts across cycles (the caller must have synced its namespace
    generation — ``runtime.finalize_batch`` does)."""
    ns_map = namespaces or {}

    def ns_labels_of(q: t.Pod) -> dict[str, str]:
        return ns_map.get(q.namespace, {})

    P = len(pods)
    N = nt.num_nodes
    NC = nt.alloc.shape[0]
    PP = max(pad_pods or P, P)

    from .encode_cache import collapse_label_groups, groups_for, pod_gids_for

    groups = groups_for(nt, cache, groups)
    any_existing_aff = any(
        affinity_has_terms(key[2]) for key in groups
    )
    any_pending_aff = any(has_any_affinity(p) for p in pods)
    if not any_existing_aff and not any_pending_aff:
        return None

    row_vocab = Vocab()
    row_meta: list[dict] = []
    row_keys: list[tuple] = []   # interned vocab key per row — the STABLE
    #                              identity the cross-cycle match cache keys on

    def row(kind: str, key: str, match_fn_sig, meta) -> int:
        vk = (kind, key, match_fn_sig)
        rid = row_vocab.intern(vk)
        if rid == len(row_meta):
            row_meta.append(dict(kind=kind, key=key, **meta))
            row_keys.append(vk)
        return rid

    def row_from_spec(spec) -> int:
        vk, meta, _inc = spec
        rid = row_vocab.intern(vk)
        if rid == len(row_meta):
            row_meta.append(dict(kind=vk[0], key=vk[1], **meta))
            row_keys.append(vk)
        return rid

    # per-pod TEMPLATE ids: the whole pending-pod side (incoming rows,
    # fa_self, update row, EA/SC slots) is a pure function of the template,
    # so it is computed once per distinct template in the batch and copied
    # to every pod stamped from it
    pod_gid = pod_gids_for(pods, cache)

    # ---- collect rows ----------------------------------------------------
    fa_slots: list[list[int]] = [[] for _ in range(P)]
    ra_slots: list[list[int]] = [[] for _ in range(P)]
    fa_self = np.zeros(PP, dtype=bool)

    tmpl_in: dict[int, tuple] = {}   # gid -> (fa rids, fa_self, ra rids)
    for i, p in enumerate(pods):
        ent = tmpl_in.get(pod_gid[i])
        if ent is None:
            fa_list: list[int] = []
            ra_list: list[int] = []
            fself = False
            aff = _req_affinity_terms(p)
            if aff:
                set_sig = (tuple(aff), p.namespace)
                for term in aff:
                    rid = row(
                        "FA", term.topology_key, ("set", set_sig),
                        dict(terms=aff, ns=p.namespace),
                    )
                    fa_list.append(rid)
                fself = all(
                    term_matches_pod(tm, p.namespace, p, ns_labels_of(p))
                    for tm in aff
                )
            for term in _req_anti_terms(p):
                rid = row(
                    "RA", term.topology_key, ("term", term, p.namespace),
                    dict(term=term, ns=p.namespace),
                )
                ra_list.append(rid)
            for wt in _pref_affinity_terms(p):
                row(
                    "SCI", wt.term.topology_key,
                    ("pref", wt.term, p.namespace),
                    dict(term=wt.term, ns=p.namespace),
                )
            for wt in _pref_anti_terms(p):
                row(
                    "SCI", wt.term.topology_key,
                    ("pref", wt.term, p.namespace),
                    dict(term=wt.term, ns=p.namespace),
                )
            ent = (fa_list, fself, ra_list)
            tmpl_in[pod_gid[i]] = ent
        fa_slots[i] = list(ent[0])
        fa_self[i] = ent[1]
        ra_slots[i] = list(ent[2])

    # rows driven by existing/assignable pods' own terms, per TEMPLATE
    # (source_row_specs — memoized across cycles by the encode cache).
    # Pending pods also contribute: once assigned in-batch they become
    # "existing" for later pods.
    def specs_of(aff, ns: str) -> tuple:
        if not affinity_has_terms(aff):
            return ()
        if cache is not None:
            key = (aff, ns)
            got = cache.aff_row_specs.get(key)
            if got is None:
                got = source_row_specs(aff, ns)
                cache.aff_row_specs.put(key, got)
            return got
        return source_row_specs(aff, ns)

    group_list: list[tuple] = []   # (labels, ns, specs, counts vec)
    for key, vec in groups.items():
        labels, ns, aff = key[0], key[1], key[2]
        specs = specs_of(aff, ns)
        for spec in specs:
            row_from_spec(spec)
        group_list.append((labels, ns, specs, vec))
    # per-template pending source specs (the per-pod specs_of lookup was a
    # deep (affinity, ns) hash per pod per cycle)
    _specs_of_gid: dict[int, tuple] = {}
    pend_specs: list[tuple] = []
    for i, p in enumerate(pods):
        sp_ = _specs_of_gid.get(pod_gid[i])
        if sp_ is None:
            sp_ = specs_of(p.affinity, p.namespace)
            _specs_of_gid[pod_gid[i]] = sp_
        pend_specs.append(sp_)
    for sp_ in _specs_of_gid.values():
        for spec in sp_:
            row_from_spec(spec)

    R = len(row_meta)
    if R == 0:
        return None

    # ---- per-row node domains + base sums --------------------------------
    key_domains: dict[str, tuple[np.ndarray, int]] = {}

    def domains_for(key: str) -> tuple[np.ndarray, int]:
        got = key_domains.get(key)
        if got is None:
            vals = nt.topology_values(key)          # (N,) interned label ids
            dom = np.full(N, -1, dtype=np.int32)
            present = vals >= 0
            n_dom = 0
            if present.any():
                uniq, first, inv = np.unique(
                    vals[present], return_index=True, return_inverse=True
                )
                # first-seen (node-order) domain ids — the same ids the
                # per-node Vocab interning loop used to produce
                rank = np.empty(len(uniq), dtype=np.int32)
                rank[np.argsort(first, kind="stable")] = np.arange(
                    len(uniq), dtype=np.int32
                )
                dom[present] = rank[inv]
                n_dom = len(uniq)
            got = (dom, n_dom)
            key_domains[key] = got
        return got

    row_domains = [domains_for(m["key"]) for m in row_meta]
    D = max((n for _, n in row_domains), default=1) or 1

    node_domain = np.full((R, NC), -1, dtype=np.int32)
    has_key = np.zeros((R, NC), dtype=bool)
    base_sums = np.zeros((R, D), dtype=np.int64)
    for r, (dom, _n) in enumerate(row_domains):
        node_domain[r, :N] = dom
        has_key[r, :N] = dom >= 0

    # does a pod shaped (labels, ns) drive row r's count — as the TARGET of
    # the row's incoming terms (FA/RA/SCI) or of an existing pod's own term
    # (EA/SCH/SCP)? One verdict per (row, template), persisted across
    # cycles by the encode cache (keyed on the stable row vocab key).
    local_match: dict = {}

    def match_group(r: int, labels, ns: str, ld: dict) -> bool:
        key = (row_keys[r], labels, ns)
        store = cache.match if cache is not None else None
        got = store.get(key) if store is not None else local_match.get(key)
        if got is None:
            meta = row_meta[r]
            nsl = ns_map.get(ns, {})
            if meta["kind"] == "FA":
                got = all(
                    term_matches(tm, meta["ns"], ns, ld, nsl)
                    for tm in meta["terms"]
                )
            else:   # single-term rows: RA/SCI/EA/SCH/SCP
                got = term_matches(meta["term"], meta["ns"], ns, ld, nsl)
            if store is not None:
                store.put(key, got)
            else:
                local_match[key] = got
        return got

    # target side: FA/RA/SCI rows count matching existing pods — segment-sum
    # each matching template's per-node counts into the row's domains
    lgroups = collapse_label_groups(groups)
    for r, meta in enumerate(row_meta):
        if meta["kind"] not in ("FA", "RA", "SCI"):
            continue
        dom, _n = row_domains[r]
        valid = dom >= 0
        if not valid.any():
            continue
        agg = None
        for (labels, ns), (vec, ld) in lgroups.items():
            if match_group(r, labels, ns, ld):
                agg = vec if agg is None else agg + vec
        if agg is not None:
            np.add.at(base_sums[r], dom[valid], agg[valid])
    # source side: rows maintained by existing pods' OWN terms — per
    # template, inc × its per-node counts into the row's domains
    for _labels, _ns, specs, vec in group_list:
        for vk, _meta, inc in specs:
            rid = row_vocab.get(vk)
            dom, _n = row_domains[rid]
            valid = dom >= 0
            if valid.any():
                np.add.at(base_sums[rid], dom[valid], inc * vec[valid])

    # ---- update matrix (in-batch assignment increments) ------------------
    update = np.zeros((PP, R), dtype=np.int64)
    tmpl_update: dict[int, np.ndarray] = {}
    for i, p in enumerate(pods):
        row_u = tmpl_update.get(pod_gid[i])
        if row_u is None:
            ld = p.labels_dict()
            row_u = np.zeros(R, dtype=np.int64)
            for r, meta in enumerate(row_meta):
                if meta["kind"] in ("FA", "RA", "SCI") and match_group(
                    r, p.labels, p.namespace, ld
                ):
                    row_u[r] += 1
            for vk, _meta, inc in pend_specs[i]:
                row_u[row_vocab.get(vk)] += inc
            tmpl_update[pod_gid[i]] = row_u
        update[i] = row_u

    # ---- filtering tensors ----------------------------------------------
    CA = max((len(s) for s in fa_slots), default=1) or 1
    CR = max((len(s) for s in ra_slots), default=1) or 1
    fa_rows = np.full((PP, CA), -1, dtype=np.int32)
    ra_rows = np.full((PP, CR), -1, dtype=np.int32)
    for i in range(P):
        for c, rid in enumerate(fa_slots[i]):
            fa_rows[i, c] = rid
        for c, rid in enumerate(ra_slots[i]):
            ra_rows[i, c] = rid

    ea_lists: list[list[int]] = []
    tmpl_ea: dict[int, list[int]] = {}
    for i, p in enumerate(pods):
        lst = tmpl_ea.get(pod_gid[i])
        if lst is None:
            ld = p.labels_dict()
            lst = [
                r for r, meta in enumerate(row_meta)
                if meta["kind"] == "EA"
                and match_group(r, p.labels, p.namespace, ld)
            ]
            tmpl_ea[pod_gid[i]] = lst
        ea_lists.append(lst)
    CE = max((len(x) for x in ea_lists), default=1) or 1
    ea_rows = np.full((PP, CE), -1, dtype=np.int32)
    for i, lst in enumerate(ea_lists):
        ea_rows[i, : len(lst)] = lst

    # ---- scoring slots ---------------------------------------------------
    sc_lists: list[list[tuple[int, int]]] = []
    tmpl_sc: dict[int, list] = {}
    for i, p in enumerate(pods):
        got_sc = tmpl_sc.get(pod_gid[i])
        if got_sc is not None:
            sc_lists.append(got_sc)
            continue
        ld = p.labels_dict()
        w: dict[int, int] = {}
        # incoming preferred terms: row counts matching existing pods; the
        # pod's own weight applies (scoring.go:98/:105)
        for wt in _pref_affinity_terms(p):
            rid = row_vocab.get(("SCI", wt.term.topology_key, ("pref", wt.term, p.namespace)))
            if rid >= 0:
                w[rid] = w.get(rid, 0) + wt.weight
        for wt in _pref_anti_terms(p):
            rid = row_vocab.get(("SCI", wt.term.topology_key, ("pref", wt.term, p.namespace)))
            if rid >= 0:
                w[rid] = w.get(rid, 0) - wt.weight
        # existing pods' terms vs this pod (scoring.go:110-124)
        for r, meta in enumerate(row_meta):
            if meta["kind"] == "SCH" and hard_pod_affinity_weight > 0:
                if match_group(r, p.labels, p.namespace, ld):
                    w[r] = w.get(r, 0) + hard_pod_affinity_weight
            elif meta["kind"] == "SCP":
                if match_group(r, p.labels, p.namespace, ld):
                    w[r] = w.get(r, 0) + meta["sign"] * meta["weight"]
        lst = sorted(w.items())
        tmpl_sc[pod_gid[i]] = lst
        sc_lists.append(lst)
    CS = max((len(x) for x in sc_lists), default=1) or 1
    score_rows = np.full((PP, CS), -1, dtype=np.int32)
    score_vals = np.zeros((PP, CS), dtype=np.int64)
    for i, lst in enumerate(sc_lists):
        for c, (rid, val) in enumerate(lst):
            score_rows[i, c] = rid
            score_vals[i, c] = val

    has_filter_work = bool(
        (fa_rows >= 0).any() or (ra_rows >= 0).any() or (ea_rows >= 0).any()
    )
    has_score_work = bool((score_rows >= 0).any())

    return PodAffinityTensors(
        node_domain=node_domain,
        has_key=has_key,
        base_sums=base_sums,
        update=update,
        fa_rows=fa_rows,
        fa_self=fa_self,
        ra_rows=ra_rows,
        ea_rows=ea_rows,
        score_rows=score_rows,
        score_vals=score_vals,
        has_filter_work=has_filter_work,
        has_score_work=has_score_work,
    )
