from .encoder import NodeTensors, PodBatch, encode_pod_batch, encode_snapshot, resource_axis, round_up  # noqa: F401
from .snapshot import Cache, NodeInfo, Snapshot  # noqa: F401
from .vocab import Vocab  # noqa: F401
