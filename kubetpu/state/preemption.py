"""Victim-slot tensor encoding for preemption.

The reference's preemption dry run copies one NodeInfo at a time and mutates
its pod list (``SelectVictimsOnNode``, framework/plugins/defaultpreemption/
default_preemption.go:252). The TPU analog needs the *per-pod-on-node*
breakdown as dense tensors: each node gets K victim slots carrying priority,
start time, resource usage, port usage counts, and PDB membership, so the
whole victim search runs as one vmapped program over all nodes at once
(vs. the reference's parallel-for over a sampled candidate subset,
framework/preemption/preemption.go:404 DryRunPreemption).

Port usage is encoded as per-triple *counts* (not the boolean union the
NodePorts filter uses): removing a victim must not free a port another
remaining pod still holds.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..api import types as t
from ..api.selectors import label_selector_matches
from . import encoder as enc
from .encoder import NodeTensors, _pod_port_triples
from .snapshot import Snapshot


@dataclass
class VictimTensors:
    """Per-node victim slots, padded to K = max pods on any node.

    ``uids[n][k]`` maps slot k of node n back to the pod uid (host side, for
    actuation); invalid slots are None.
    """

    uids: list[list[str | None]]
    valid: np.ndarray          # (N, K) bool
    priority: np.ndarray       # (N, K) int64
    start: np.ndarray          # (N, K) int64 — creation_index stand-in for
    #                            pod start time (util.GetPodStartTime)
    requests: np.ndarray       # (N, K, R) int64 — exact requests view
    port_counts: np.ndarray    # (N, Kp) int32 — pods-per-triple on the node
    victim_ports: np.ndarray   # (N, K, Kp) int8 — victim's triples (0/1)
    pdb: np.ndarray            # (N, K, D) bool — victim matches PDB d
    pdb_allowed: np.ndarray    # (D,) int64 — status.disruptionsAllowed

    @property
    def num_slots(self) -> int:
        return self.valid.shape[1]


def encode_victims(
    nt: NodeTensors,
    port_vocab_size: int,
    port_vocab,
    pdbs: tuple[t.PodDisruptionBudget, ...] = (),
    pad_slots: int | None = None,
) -> VictimTensors:
    """Build victim tensors from the encoded snapshot's NodeInfos.

    ``port_vocab`` must be the SAME interning used for the batch's
    pod_ports/node_ports/port_conflict tensors (encoder._encode_ports) so the
    preemption kernel's port math composes with the filter's conflict matrix.
    """
    infos = nt.infos
    N = nt.alloc.shape[0]            # padded node capacity
    R = nt.num_resources
    K = max((len(info.pods) for info in infos), default=0)
    K = max(enc.round_up(K, minimum=4) if pad_slots is None else pad_slots, 1)
    Kp = max(port_vocab_size, 1)
    D = max(len(pdbs), 1)

    uids: list[list[str | None]] = [[None] * K for _ in range(N)]
    valid = np.zeros((N, K), dtype=bool)
    priority = np.zeros((N, K), dtype=np.int64)
    start = np.zeros((N, K), dtype=np.int64)
    requests = np.zeros((N, K, R), dtype=np.int64)
    port_counts = np.zeros((N, Kp), dtype=np.int32)
    victim_ports = np.zeros((N, K, Kp), dtype=np.int8)
    pdb = np.zeros((N, K, D), dtype=bool)
    ridx = {r: i for i, r in enumerate(nt.resource_names)}

    for n_i, info in enumerate(infos):
        for k_i, pod in enumerate(info.pods.values()):
            uids[n_i][k_i] = pod.uid
            valid[n_i, k_i] = True
            priority[n_i, k_i] = pod.priority
            start[n_i, k_i] = pod.creation_index
            for rname, v in pod.requests:
                j = ridx.get(rname)
                if j is not None:
                    requests[n_i, k_i, j] = v
            for triple in _pod_port_triples(pod):
                tid = port_vocab.get(triple)
                if tid is not None and tid >= 0:
                    port_counts[n_i, tid] += 1
                    victim_ports[n_i, k_i, tid] = 1
            labels = pod.labels_dict()
            for d_i, b in enumerate(pdbs):
                # default_preemption.go:416-443: namespace match, non-empty
                # selector match, and not already in status.disruptedPods.
                if b.namespace != pod.namespace or not labels:
                    continue
                if b.selector is None:
                    continue
                if (
                    not b.selector.match_labels
                    and not b.selector.match_expressions
                ):
                    continue  # empty selector matches nothing (policy/v1)
                if pod.name in b.disrupted_pods:
                    continue
                if label_selector_matches(b.selector, labels):
                    pdb[n_i, k_i, d_i] = True

    pdb_allowed = np.zeros(D, dtype=np.int64)
    for d_i, b in enumerate(pdbs):
        pdb_allowed[d_i] = b.disruptions_allowed

    return VictimTensors(
        uids=uids,
        valid=valid,
        priority=priority,
        start=start,
        requests=requests,
        port_counts=port_counts,
        victim_ports=victim_ports,
        pdb=pdb,
        pdb_allowed=pdb_allowed,
    )
