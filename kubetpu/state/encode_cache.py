"""Cross-cycle encode cache — event-time, template-keyed pod tensorization.

The r05 fullstack trace showed host encode eating 86% of the scheduling
cycle (116ms of 134.4ms per 128-pod cycle) while the device assign took
15.7ms: the tensorization layer rebuilt every static per-pod row from
scratch each cycle even though PR 2 already made the *device* side O(Δ).
This module closes the host side of that gap, in three layers:

1. **Event-time pre-encoding** — the scheduler's informer handlers
   (``on_pod_add``/``on_pod_update``) call ``precompute_pod`` when a pending
   pod is delivered, so its static rows (filter mask, NodeAffinity /
   TaintToleration score rows, request row) are built OFF the cycle
   critical path. Cycle-time ``encode_pod_batch`` then *gathers* rows out
   of this cache instead of rebuilding them.
2. **Template-keyed row sharing** — rows are keyed by the pod's *static
   signatures* (``_static_filter_signature`` / ``_static_score_signature``
   / the request tuple), not by pod identity: pods stamped from one
   Deployment/Job template are spec-identical, so a 1000-pod burst from 3
   templates encodes ~3 rows — shared across pods AND across cycles, with
   an LRU bound and hit/miss counters surfaced through
   ``TPUBackendMetrics``.
3. **Invalidation by construction** — a row is a pure function of its
   signature key plus the node static facts, so pod mutation can never
   leave a stale row behind (a mutated pod hashes to a *different* key);
   node-side staleness is handled by an epoch the scheduler bumps on every
   node add/update/delete (``invalidate_nodes``), which clears the
   node-dependent caches wholesale. Rows involving per-batch coupled state
   (volumes, DRA, folded singleton scalars, in-batch RWOP duplicates) are
   never cached here — the batch encoder layers those onto a *copy* of the
   cached base row.

The persistent inter-pod-affinity / topology-spread term caches
(``aff_row_specs``, ``match``, ``sel_counts``) live here too: they memoize
the per-*template* term→row specs and selector-match verdicts that
``state.podaffinity`` / ``state.spread`` previously recomputed per existing
pod per cycle (the other 60% of the r05 encode wall). Namespace-label
changes clear the match caches (affinity namespaceSelectors match against
namespace labels).
"""

from __future__ import annotations

import collections
from dataclasses import dataclass
from typing import Callable

import numpy as np


_MISSING = object()


class _LRU:
    """Tiny bounded mapping: least-recently-USED eviction via OrderedDict
    (get refreshes recency). Eviction is always safe — every entry can be
    rebuilt from its key."""

    __slots__ = ("_d", "maxlen")

    def __init__(self, maxlen: int) -> None:
        self._d: "collections.OrderedDict" = collections.OrderedDict()
        self.maxlen = maxlen

    def get(self, key, default=None):
        d = self._d
        got = d.get(key, _MISSING)
        if got is _MISSING:
            return default
        d.move_to_end(key)
        return got

    def put(self, key, value) -> None:
        d = self._d
        d[key] = value
        d.move_to_end(key)
        if len(d) > self.maxlen:
            d.popitem(last=False)

    def pop(self, key) -> None:
        self._d.pop(key, None)

    def clear(self) -> None:
        self._d.clear()

    def __len__(self) -> int:
        return len(self._d)

    def __contains__(self, key) -> bool:
        return key in self._d


def template_key(pod) -> tuple:
    """The pod's TEMPLATE identity: every spec fact the per-pod halves of
    the spread/affinity encoders read. Pods stamped from one controller
    template share it, so per-pod work collapses to per-template work.
    Index [0:3] — (labels, namespace, affinity) — is what the existing-pod
    group consumers (base sums, selector counts) key on."""
    return (
        pod.labels, pod.namespace, pod.affinity,
        pod.topology_spread_constraints, pod.tolerations, pod.node_selector,
    )


class _BoundedMemo(dict):
    """Plain-dict memo with a size bound enforced by wholesale clear —
    for per-POD hot paths (uid → memo) where an OrderedDict's per-get
    recency bookkeeping costs more than the occasional full recompute."""

    __slots__ = ("maxlen",)

    def __init__(self, maxlen: int) -> None:
        super().__init__()
        self.maxlen = maxlen

    def put(self, key, value) -> None:
        if len(self) >= self.maxlen:
            self.clear()
        self[key] = value


@dataclass
class NodeCtx:
    """Node-side facts the static row builders consume, hoisted once per
    node epoch (they only change when a node is added/updated/removed —
    exactly the events that bump the epoch): taint tuples, the
    unschedulable mask, and declared-feature sets."""

    node_taints: list               # per node: tuple of taints
    tainted_nodes: list             # [(node_idx, taints)] for tainted only
    node_unsched: np.ndarray        # (N,) bool
    any_unsched: bool
    node_feature_sets: list | None  # per node set() or None when none declare


def build_node_ctx(nt) -> NodeCtx:
    node_taints = [info.node.taints for info in nt.infos]
    tainted = [(i, tt) for i, tt in enumerate(node_taints) if tt]
    unsched = np.array(
        [info.node.unschedulable for info in nt.infos], dtype=bool
    )
    feature_sets = (
        [set(info.node.declared_features) for info in nt.infos]
        if any(info.node.declared_features for info in nt.infos) else None
    )
    return NodeCtx(
        node_taints=node_taints,
        tainted_nodes=tainted,
        node_unsched=unsched,
        any_unsched=bool(unsched.any()),
        node_feature_sets=feature_sets,
    )


#: past this many cached rows a scoped extension costs more python than the
#: full-miss storm it avoids — wholesale clear instead (templates in real
#: workloads number in the dozens, so the cap only bites pathological keys)
EXTEND_MAX_ENTRIES = 1024


class EncodeCache:
    """See module docstring. Single-owner like the scheduler loop: informer
    callbacks and the encode path run on the loop thread.

    ``scoped=True`` (default) keeps node-epoch invalidation SCOPED: a node
    ADD (``invalidate_nodes(added=...)``) extends every cached row with the
    appended nodes' columns at the next sync — O(templates × Δnodes) —
    instead of clearing all node-dependent stores (at 100k nodes under an
    autoscaler add-wave the wholesale clear was a full re-encode storm per
    event). A node DELETE (``invalidate_nodes(removed=...)``) is scoped
    too: the next sync maps the rebuilt tensors' node names back to the
    old indices and COMPACTS every cached row by gathering the survivor
    columns — rows are pure per-node functions, so the gather is
    bit-identical to a fresh build (the drain-wave twin of the add-wave
    extension; ROADMAP 5b). Only updates (facts change at an interior
    index) and mixed add+remove waves still pay the full-epoch flush
    through the bare ``invalidate_nodes()`` seam. ``scoped=False`` is the
    escape hatch / A-B control: every epoch bump clears wholesale, the
    pre-PR-14 behavior."""

    def __init__(
        self, max_entries: int = 8192, metrics=None, scoped: bool = True,
    ) -> None:
        self.max_entries = max_entries
        self.scoped = scoped
        self.extend_max_entries = EXTEND_MAX_ENTRIES
        # --- node-fact versioning ---------------------------------------
        # bumped by the scheduler on EVERY node add/update/delete; rows are
        # valid only while built against (this epoch, this NodeTensors)
        self.node_epoch = 0
        # bumped only on FULL flushes (bare invalidate_nodes, or scoped
        # off): the template-group index keys on this, so an add-wave
        # extends its count vectors instead of rebuilding them wholesale
        self._full_epoch = 0
        self._pending_adds = 0        # scoped adds since the last sync
        self._pending_removes = 0     # scoped removals since the last sync
        self._pending_full = False    # a full flush is owed at next sync
        self._nt_len = -1             # node count rows were built against
        self._nt_token: object | None = None   # adopted NodeTensors
        self._nt_epoch = -1                    # epoch rows were built at
        self._ctx: NodeCtx | None = None
        # --- template-keyed row stores ----------------------------------
        self._filter_rows = _LRU(max_entries)  # key -> (row (N,) bool, trivial)
        self._score_rows = _LRU(max_entries)   # key -> (na_vec, tt_vec)
        self._request_rows = _LRU(max_entries)
        self._req_token: tuple | None = None   # (axis tuple, folded frozenset)
        # per-pod signature memo: uid -> (pod object, filter_sig, score_sig)
        # — identity-checked so a replaced (mutated) pod can NEVER reuse the
        # previous object's signatures
        self._pod_sigs = _BoundedMemo(max_entries * 8)
        # --- incremental template-group index ---------------------------
        # per-node {group_key: count} + the node generation folded in, and
        # the aggregated (N,) count vectors — pod_groups() refreshes only
        # nodes whose generation moved (the snapshot's O(Δ) discipline
        # extended to the template grouping pass)
        self._groups_nt: object | None = None
        self._groups_epoch = -1
        self._group_vecs: dict = {}    # gid -> (N,) int64
        self._group_node: dict = {}    # node name -> {gid: count}
        self._group_gens: dict = {}
        # template keys interned to small ints: the deep (labels, ns,
        # affinity) hash is paid once per pod OBJECT (uid-memoized,
        # identity-checked), not once per pod per cycle
        self._group_ids: dict = {}     # (labels, ns, affinity) -> gid
        self._group_keys: list = []    # gid -> key
        self._pod_group_ids = _BoundedMemo(max_entries * 8)
        # --- persistent affinity / spread term caches -------------------
        self._ns_gen: int | None = None
        # (affinity, ns) -> tuple of source-row specs (state.podaffinity)
        self.aff_row_specs = _LRU(max_entries)
        # (row_key, labels, ns) -> bool — does a pod shaped (labels, ns)
        # drive / match this affinity row
        self.match = _LRU(max_entries)
        # (selector, labels) -> bool — countPodsMatchSelector verdict
        self.sel_counts = _LRU(max_entries)
        # --- counters (plain ints: hot-loop cheap; mirrored into the
        # prom registry per cycle by flush_metrics) ----------------------
        self.hits: collections.Counter = collections.Counter()
        self.misses: collections.Counter = collections.Counter()
        self.invalidations = 0
        # re-encode work accounting (the node-wave evidence): bytes of rows
        # built from scratch on a miss vs bytes of delta columns appended
        # by scoped extensions, and how many syncs extended vs flushed
        self.rebuilt_bytes = 0
        self.extended_bytes = 0
        self.scoped_extensions = 0
        self.scoped_removals = 0
        self.compacted_bytes = 0      # row bytes dropped by removal gathers
        self._flushed_hits: collections.Counter = collections.Counter()
        self._flushed_misses: collections.Counter = collections.Counter()
        self._flushed_invalidations = 0
        self.metrics = metrics   # TPUBackendMetrics | None

    # ------------------------------------------------------------ epochs
    def invalidate_nodes(self, added=None, removed=None) -> None:
        """A node event landed. Bare call — the BLESSED full-epoch seam
        for updates: every node-dependent row is suspect and the next
        sync clears wholesale. ``added=<node>`` — a scoped node ADD: the
        next sync EXTENDS cached rows with the appended nodes' columns
        instead of clearing. ``removed=<node>`` — a scoped node DELETE
        (the drain wave): the next sync COMPACTS cached rows down to the
        surviving nodes' columns by an old-index gather, falling back to
        the wholesale clear when the wave turns out to be mixed
        (graftcheck EC001 pins bare calls to the scheduler's node event
        handlers so this scoping can't silently regress to a
        flush-per-event storm). O(1) every way — all real work is
        deferred to the next sync."""
        self.node_epoch += 1
        if removed is not None and self.scoped:
            self._pending_removes += 1
        elif added is not None and self.scoped:
            self._pending_adds += 1
        else:
            self._pending_full = True
            self._full_epoch += 1

    def sync_nodes(self, nt) -> bool:
        """Adopt ``nt`` (the NodeTensors the current encode runs against).
        When every epoch bump since the last sync was a scoped ADD and the
        encoder extended the SAME tensors object in place, cached rows are
        extended with the appended nodes' columns (O(templates × Δ));
        otherwise the node-dependent stores clear wholesale. Returns True
        when a wholesale invalidation happened (for the encode span's
        trace attrs)."""
        if (
            self._nt_token is nt
            and self._nt_epoch == self.node_epoch
            and self._nt_len == nt.num_nodes
        ):
            return False
        # same-object growth is append-only BY CONSTRUCTION: the encoder
        # mutates tensors in place only when the old rows are a preserved
        # prefix. Gating on observed growth (not just the pending-add
        # counter) also covers appends that bypass the node informer —
        # e.g. a placeholder node born from an assigned pod on an
        # unknown node.
        if (
            self.scoped
            and not self._pending_full
            and not self._pending_removes
            and self._nt_token is nt
            and 0 <= self._nt_len < nt.num_nodes
            and (len(self._filter_rows) + len(self._score_rows))
            <= self.extend_max_entries
        ):
            self._extend_rows(nt, self._nt_len)
            self._nt_epoch = self.node_epoch
            self._nt_len = nt.num_nodes
            self._pending_adds = 0
            self.scoped_extensions += 1
            return False    # rows stayed valid — not an invalidation
        # removal-only wave: deletes rebuild the tensors, so the NEW
        # object's node names are mapped back to old indices and every
        # cached row is compacted by a survivor gather — bit-identical to
        # a fresh build (rows are pure per-node functions and no
        # survivor's facts changed). Any name the old axis doesn't know
        # (a mixed wave) falls through to the wholesale clear.
        if (
            self.scoped
            and not self._pending_full
            and self._pending_removes
            and not self._pending_adds
            and nt is not None
            and self._nt_token is not None
            and self._nt_token is not nt
            and (len(self._filter_rows) + len(self._score_rows))
            <= self.extend_max_entries
        ):
            keep = self._removal_keep(nt)
            if keep is not None:
                self._compact_rows(nt, keep)
                self._nt_token = nt
                self._nt_epoch = self.node_epoch
                self._nt_len = nt.num_nodes
                self._pending_removes = 0
                self.scoped_removals += 1
                return False    # rows stayed valid — not an invalidation
        self._filter_rows.clear()
        self._score_rows.clear()
        self._ctx = None
        invalidated = self._nt_token is not None
        self._nt_token = nt
        self._nt_epoch = self.node_epoch
        self._nt_len = nt.num_nodes if nt is not None else -1
        self._pending_adds = 0
        self._pending_removes = 0
        self._pending_full = False
        if invalidated:
            self.invalidations += 1
        return invalidated

    def _extend_rows(self, nt, start: int) -> None:
        """Append the columns for nodes [start:) to every cached filter /
        score row: each row is a pure function of (node facts, stored
        pod's signature), so the delta columns are built against a VIEW of
        only the appended nodes and concatenated — bit-identical to a
        fresh full-width build, at O(templates × Δnodes) cost."""
        from . import encoder as enc

        d_nt = _delta_tensors(nt, start)
        d_ctx = build_node_ctx(d_nt)
        ctx = self._ctx
        if ctx is not None:
            ctx.node_taints.extend(d_ctx.node_taints)
            ctx.tainted_nodes.extend(
                (start + i, tt) for i, tt in d_ctx.tainted_nodes
            )
            ctx.node_unsched = np.concatenate(
                [ctx.node_unsched, d_ctx.node_unsched]
            )
            ctx.any_unsched = bool(ctx.any_unsched or d_ctx.any_unsched)
            if d_ctx.node_feature_sets is not None and (
                ctx.node_feature_sets is None
            ):
                # first declaring node arrived in the delta: the hoist
                # needs per-node sets for the OLD nodes too — rebuild
                self._ctx = build_node_ctx(nt)
            elif ctx.node_feature_sets is not None:
                ctx.node_feature_sets.extend(
                    d_ctx.node_feature_sets
                    if d_ctx.node_feature_sets is not None
                    else [set() for _ in range(nt.num_nodes - start)]
                )
        fd = self._filter_rows._d
        for key in list(fd.keys()):
            row, trivial, pod = fd[key]
            _fsig, feat_req, _nn, unknown, f = key
            delta = enc.build_static_filter_row(
                d_nt, d_ctx, pod, f, feat_req, unknown
            )
            fd[key] = (
                np.concatenate([row, delta]),
                bool(trivial and delta.all()),
                pod,
            )
            self.extended_bytes += delta.nbytes
        sd = self._score_rows._d
        for key in list(sd.keys()):
            na, tt, pod = sd[key]
            _ssig, want_na, want_tt = key
            dna, dtt = enc.build_static_score_rows(
                d_nt, d_ctx, pod, want_na, want_tt
            )
            sd[key] = (
                np.concatenate([na, dna]), np.concatenate([tt, dtt]), pod,
            )
            self.extended_bytes += dna.nbytes + dtt.nbytes

    def _removal_keep(self, nt) -> "np.ndarray | None":
        """Map the rebuilt tensors' node names back to old row indices:
        ``keep[j]`` = the old index of new node j. None when the mapping
        is not a pure survivor gather — an unknown name means the wave
        also ADDED a node (mixed: wholesale), and a stale old token
        (mutated past the rows' length) can't be trusted as the source
        axis."""
        old_names = getattr(self._nt_token, "node_names", None)
        if old_names is None or len(old_names) != self._nt_len:
            return None
        if nt.num_nodes >= len(old_names):
            return None     # nothing was removed — not a drain wave
        pos = {name: i for i, name in enumerate(old_names)}
        keep = np.empty(nt.num_nodes, dtype=np.int64)
        for j, name in enumerate(nt.node_names):
            i = pos.get(name)
            if i is None:
                return None
            keep[j] = i
        return keep

    def _compact_rows(self, nt, keep: np.ndarray) -> None:
        """Gather the survivor columns out of every cached row (and the
        hoisted node ctx / group count vectors): ``row[keep]`` reorders
        old columns into the new axis order, which is bit-identical to
        rebuilding each row against the new tensors because rows are
        pure per-node functions and a removal-only wave changes no
        survivor's facts."""
        old_n = self._nt_len
        ctx = self._ctx
        if ctx is not None:
            ctx.node_taints = [ctx.node_taints[i] for i in keep]
            ctx.tainted_nodes = [
                (j, tt) for j, tt in enumerate(ctx.node_taints) if tt
            ]
            ctx.node_unsched = ctx.node_unsched[keep]
            ctx.any_unsched = bool(ctx.node_unsched.any())
            if ctx.node_feature_sets is not None:
                nfs = [ctx.node_feature_sets[i] for i in keep]
                # fresh build_node_ctx collapses to None when no node
                # declares features — match it so downstream branches
                # (feature filter on/off) stay identical
                ctx.node_feature_sets = nfs if any(nfs) else None
        fd = self._filter_rows._d
        for key in list(fd.keys()):
            row, _trivial, pod = fd[key]
            row2 = row[keep]
            fd[key] = (row2, bool(row2.all()), pod)
            self.compacted_bytes += max(row.nbytes - row2.nbytes, 0)
        sd = self._score_rows._d
        for key in list(sd.keys()):
            na, tt, pod = sd[key]
            na2, tt2 = na[keep], tt[keep]
            sd[key] = (na2, tt2, pod)
            self.compacted_bytes += max(
                na.nbytes + tt.nbytes - na2.nbytes - tt2.nbytes, 0
            )
        # the incremental template-group index rides along: gather its
        # count vectors and drop the removed nodes' per-node entries, so
        # the next pod_groups() stays O(Δ) instead of re-deriving every
        # node after the drain wave
        if (
            self._groups_nt is self._nt_token
            and self._groups_epoch == self._full_epoch
        ):
            vecs = self._group_vecs
            for gid, vec in list(vecs.items()):
                if len(vec) < old_n:
                    vec = np.concatenate(
                        [vec, np.zeros(old_n - len(vec), dtype=np.int64)]
                    )
                vecs[gid] = vec[keep]
            gone = set(getattr(self._nt_token, "node_names", ())) - set(
                nt.node_names
            )
            for name in gone:
                self._group_node.pop(name, None)
                self._group_gens.pop(name, None)
            self._groups_nt = nt

    def fresh_for(self, nt) -> bool:
        """May event-time precompute build rows against ``nt`` right now?
        Only when ``nt`` is the adopted tensors AND no node event landed
        since they were encoded (a bumped epoch means ``nt`` no longer
        reflects the node set — rows built from it would be stale)."""
        return (
            nt is not None
            and self._nt_token is nt
            and self._nt_epoch == self.node_epoch
            and self._nt_len == nt.num_nodes
        )

    def node_ctx(self, nt) -> NodeCtx:
        ctx = self._ctx
        if ctx is None or self._nt_token is not nt:
            ctx = build_node_ctx(nt)
            if self._nt_token is nt:
                self._ctx = ctx
        return ctx

    def sync_namespaces(self, ns_gen: int) -> None:
        """Namespace labels feed affinity-term namespaceSelectors — any
        namespace change invalidates the persistent match verdicts."""
        if self._ns_gen != ns_gen:
            if self._ns_gen is not None:
                self.match.clear()
                self.invalidations += 1
            self._ns_gen = ns_gen

    def sync_request_axis(self, axis: tuple, folded: frozenset) -> None:
        """Request rows are laid out on the batch's resource axis; the
        ``unknown`` flag additionally depends on the folded set. A changed
        (axis, folded) token clears the request-row store."""
        token = (axis, folded)
        if self._req_token != token:
            self._request_rows.clear()
            self._req_token = token

    # ----------------------------------------------------- row accessors
    # Entries carry a representative POD alongside the row: any pod whose
    # signature hashes to the key rebuilds the identical row (rows are pure
    # functions of the key + node facts), which is what lets a scoped node
    # ADD extend cached rows with freshly built delta columns.
    def filter_row(self, key, build: Callable[[], np.ndarray], pod=None):
        """(row, trivial) for a pure-static filter signature key."""
        got = self._filter_rows.get(key)
        if got is not None:
            self.hits["filter"] += 1
            return got[0], got[1]
        self.misses["filter"] += 1
        row = build()
        self.rebuilt_bytes += row.nbytes
        entry = (row, bool(row.all()))
        self._filter_rows.put(key, entry + (pod,))
        return entry

    def score_row(self, key, build: Callable[[], tuple], pod=None):
        got = self._score_rows.get(key)
        if got is not None:
            self.hits["score"] += 1
            return got[0], got[1]
        self.misses["score"] += 1
        entry = build()
        self.rebuilt_bytes += entry[0].nbytes + entry[1].nbytes
        self._score_rows.put(key, entry + (pod,))
        return entry

    def request_row(self, key, build: Callable[[], tuple]):
        got = self._request_rows.get(key)
        if got is not None:
            self.hits["request"] += 1
            return got
        self.misses["request"] += 1
        entry = build()
        self._request_rows.put(key, entry)
        return entry

    # ------------------------------------------------- per-pod signatures
    def pod_sigs(self, pod) -> tuple:
        """(filter_sig, score_sig) for ``pod``, memoized by uid and
        verified by OBJECT IDENTITY — an informer update replaces the pod
        object, so a stale memo can never answer for a mutated pod."""
        from .encoder import _static_filter_signature, _static_score_signature

        got = self._pod_sigs.get(pod.uid)
        if got is not None and got[0] is pod:
            self.hits["pod_sig"] += 1
            return got[1], got[2]
        self.misses["pod_sig"] += 1
        fsig = _static_filter_signature(pod)
        ssig = _static_score_signature(pod)
        self._pod_sigs.put(pod.uid, (pod, fsig, ssig))
        return fsig, ssig

    def drop_pod(self, uid: str) -> None:
        self._pod_sigs.pop(uid, None)
        self._pod_group_ids.pop(uid, None)

    # ------------------------------------------------ event-time pre-encode
    def precompute_pod(self, nt, pod, enabled_filters, enabled_scores) -> bool:
        """Event-time hook: build (or touch) the pod's static rows NOW, off
        the cycle critical path. No-op unless ``fresh_for(nt)`` — after a
        node event the rows must wait for the next cycle's re-adopt.
        Returns True when the rows are present afterwards."""
        from . import encoder as enc

        if not self.fresh_for(nt):
            return False
        fsig, ssig = self.pod_sigs(pod)
        ctx = self.node_ctx(nt)
        f = enc.names.ALL_FILTERS if enabled_filters is None else enabled_filters
        # request row first: its ``unknown`` verdict is part of the filter
        # key (only possible once a batch has established the axis token)
        unknown = False
        if self._req_token is not None:
            axis, folded = self._req_token
            ridx = {r: i for i, r in enumerate(axis)}
            key = (pod.requests, pod.nonzero, ())
            entry = self.request_row(
                key,
                lambda: enc.build_request_row(pod, ridx, len(axis), folded, ()),
            )
            unknown = entry[2]
        feat_req = (
            pod.required_node_features
            if enc.names.NODE_DECLARED_FEATURES in f else ()
        )
        fkey = (
            fsig, feat_req,
            pod.node_name if enc.names.NODE_NAME in f else "",
            bool(unknown) and enc.names.NODE_RESOURCES_FIT in f,
            f,   # the RESOLVED set — must match the batch encoder's key
        )
        self.filter_row(
            fkey,
            lambda: enc.build_static_filter_row(
                nt, ctx, pod, f, feat_req, fkey[3]
            ),
            pod,
        )
        sc = (
            enc.DEFAULT_SCORES if enabled_scores is None else enabled_scores
        )
        want_na = enc.names.NODE_AFFINITY in sc
        want_tt = enc.names.TAINT_TOLERATION in sc
        if want_na or want_tt:
            skey = (ssig, want_na, want_tt)
            self.score_row(
                skey,
                lambda: enc.build_static_score_rows(
                    nt, ctx, pod, want_na, want_tt
                ),
                pod,
            )
        return True

    # ------------------------------------------------ template-group index
    def group_id_of(self, pod) -> int:
        """Small-int id of the pod's TEMPLATE ``(labels, namespace,
        affinity)`` — the deep key hash is paid once per pod OBJECT
        (uid-memoized, identity-checked), after which template membership
        is an int."""
        got = self._pod_group_ids.get(pod.uid)
        if got is not None and got[0] is pod:
            return got[1]
        key = template_key(pod)
        gid = self._group_ids.get(key)
        if gid is None:
            gid = len(self._group_keys)
            self._group_ids[key] = gid
            self._group_keys.append(key)
        self._pod_group_ids.put(pod.uid, (pod, gid))
        return gid

    def pod_groups(self, nt) -> dict:
        """``collect_pod_groups(nt)``, maintained incrementally: only nodes
        whose generation moved since the last call re-derive their
        per-template counts (O(Δ nodes × pods-per-node) per cycle instead
        of O(all assigned pods)). Rebuilt wholesale when the tensors were
        replaced or a FULL-epoch flush landed (update/delete); scoped node
        ADDS just grow the count vectors in place. Returned vectors are
        LIVE index state — callers must not mutate them."""
        if len(self._group_keys) > (1 << 16):
            # template-id interning ran away (per-pod-unique labels): reset
            # the whole index — gids are invalidated with it
            self._group_ids = {}
            self._group_keys = []
            self._pod_group_ids.clear()
            self._groups_nt = None
        if self._groups_nt is not nt or self._groups_epoch != self._full_epoch:
            self._group_vecs = {}
            self._group_node = {}
            self._group_gens = {}
            self._groups_nt = nt
            self._groups_epoch = self._full_epoch
        N = nt.num_nodes
        gens = nt.node_gens
        vecs = self._group_vecs
        # scoped node ADDS grow the node axis in place: extend the count
        # vectors with zeros (appended nodes' pods fold in via the gens
        # loop below — their generations are unseen)
        for gid, vec in list(vecs.items()):
            if len(vec) < N:
                vecs[gid] = np.concatenate(
                    [vec, np.zeros(N - len(vec), dtype=np.int64)]
                )
        for i, info in enumerate(nt.infos):
            name = nt.node_names[i]
            g = gens.get(name)
            if self._group_gens.get(name) == g:
                continue
            old = self._group_node.get(name)
            if old:
                for gid, c in old.items():
                    vec = vecs.get(gid)
                    if vec is not None:
                        vec[i] -= c
            new: dict = {}
            for q in info.pods.values():
                gid = self.group_id_of(q)
                new[gid] = new.get(gid, 0) + 1
            for gid, c in new.items():
                vec = vecs.get(gid)
                if vec is None:
                    vec = np.zeros(N, dtype=np.int64)
                    vecs[gid] = vec
                vec[i] += c
            self._group_node[name] = new
            self._group_gens[name] = g
        return {
            self._group_keys[gid]: v for gid, v in vecs.items() if v.any()
        }

    # ----------------------------------------------------------- metrics
    def stats(self) -> dict:
        h, m = sum(self.hits.values()), sum(self.misses.values())
        return {
            "hits": h,
            "misses": m,
            "hit_rate": (h / (h + m)) if (h + m) else None,
            "entries": len(self._filter_rows) + len(self._score_rows)
            + len(self._request_rows),
            "invalidations": self.invalidations,
            # re-encode work: bytes built from scratch on misses vs bytes
            # appended by scoped extensions (the node-wave evidence the
            # tier-1 scoped-vs-flush test and trace records assert on)
            "rebuilt_bytes": self.rebuilt_bytes,
            "extended_bytes": self.extended_bytes,
            "scoped_extensions": self.scoped_extensions,
            "scoped_removals": self.scoped_removals,
            "compacted_bytes": self.compacted_bytes,
        }

    def hit_rate(self, kinds=("filter", "score", "request")) -> float | None:
        h = sum(self.hits[k] for k in kinds)
        m = sum(self.misses[k] for k in kinds)
        return (h / (h + m)) if (h + m) else None

    def flush_metrics(self) -> dict:
        """Mirror the counter deltas since the last flush into the prom
        registry (TPUBackendMetrics) and return them — the scheduler calls
        this once per cycle and attaches the deltas to the encode span."""
        delta = {"hits": 0, "misses": 0}
        for kind in set(self.hits) | set(self._flushed_hits):
            d = self.hits[kind] - self._flushed_hits[kind]
            if d:
                delta["hits"] += d
                self._flushed_hits[kind] = self.hits[kind]
                if self.metrics is not None:
                    self.metrics.encode_cache_hits.labels(kind).inc(d)
        for kind in set(self.misses) | set(self._flushed_misses):
            d = self.misses[kind] - self._flushed_misses[kind]
            if d:
                delta["misses"] += d
                self._flushed_misses[kind] = self.misses[kind]
                if self.metrics is not None:
                    self.metrics.encode_cache_misses.labels(kind).inc(d)
        inv = self.invalidations - self._flushed_invalidations
        if inv:
            delta["invalidations"] = inv
            self._flushed_invalidations = self.invalidations
        if self.metrics is not None:
            self.metrics.encode_cache_entries.set(self.stats()["entries"])
        return delta


def _delta_tensors(nt, start: int):
    """A minimal NodeTensors VIEW over only the appended nodes
    [start:num_nodes) — just what the static row builders consume (names,
    infos, label machinery; resource arrays are not read by them). Fresh
    vocabs: the view is self-contained, ids never leak into ``nt``."""
    from .encoder import NodeTensors

    d = nt.num_nodes - start
    z2 = np.zeros((d, 0), dtype=np.int64)
    sub = NodeTensors(
        resource_names=[],
        node_names=list(nt.node_names[start:]),
        alloc=z2,
        requested=z2,
        nonzero_requested=z2,
        pod_count=np.zeros(d, dtype=np.int32),
        allowed_pods=np.zeros(d, dtype=np.int32),
        infos=list(nt.infos[start:]),
    )
    # intern the appended nodes' labels (the full build does this too) —
    # requirement_mask treats an un-interned key as absent-on-every-node,
    # which would extend selector/affinity rows with all-False columns
    for info in sub.infos:
        for k, v in info.node.labels:
            sub.key_vocab.intern(k)
            sub.val_vocab.intern(v)
    return sub


def groups_for(nt, cache, groups: dict | None = None) -> dict:
    """The template-group view for an encode: the precomputed ``groups``
    when the caller already built them, else the cache's incremental index,
    else a from-scratch pass. The single place that decides."""
    if groups is not None:
        return groups
    if cache is not None:
        return cache.pod_groups(nt)
    return collect_pod_groups(nt)


def pod_gids_for(pods, cache) -> list:
    """Per-pod template ids for a pending batch: the cache's uid-memoized
    global ids, or call-local first-seen ids when no cache is wired."""
    if cache is not None:
        return [cache.group_id_of(p) for p in pods]
    local: dict = {}
    return [
        local.setdefault(template_key(p), len(local)) for p in pods
    ]


def collapse_label_groups(groups: dict) -> dict:
    """Collapse template groups to ``{(labels, ns): [counts, labels
    dict]}`` — the view selector matching consumes (selectors never look
    past the counted pod's labels and namespace)."""
    out: dict = {}
    for key, vec in groups.items():
        got = out.get(key[:2])
        if got is None:
            out[key[:2]] = [vec.copy(), dict(key[0])]
        else:
            got[0] += vec
    return out


def collect_pod_groups(nt) -> dict:
    """One pass over the snapshot's assigned pods, grouped by TEMPLATE:
    ``{template_key(pod): (N,) int64 per-node counts}``.

    Pods stamped from one controller template share the key, so the group
    count is tiny regardless of pod count — the per-(existing pod × row)
    Python loops in ``state.podaffinity`` / ``state.spread`` collapse to
    per-(template × row) numpy segment sums over these vectors. O(total
    assigned pods) dict work, no row logic per pod. (``EncodeCache.
    pod_groups`` is the incremental O(Δ) twin of this function.)"""
    N = nt.num_nodes
    groups: dict = {}
    for n_i, info in enumerate(nt.infos):
        for q in info.pods.values():
            key = template_key(q)
            vec = groups.get(key)
            if vec is None:
                vec = np.zeros(N, dtype=np.int64)
                groups[key] = vec
            vec[n_i] += 1
    return groups


