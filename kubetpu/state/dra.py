"""Dynamic Resource Allocation — tensorization + host allocator.

Reference surfaces mirrored:

- ``pkg/scheduler/framework/plugins/dynamicresources/dynamicresources.go``:
  PreEnqueue (claims must exist :270), PreFilter claim/class validation
  (:444, :668), Filter = "can every unallocated claim be allocated on this
  node" (:734), Reserve allocates in-memory (:1146), Unreserve rolls back
  (:1255), PreBind writes claim status (:1334), Score rewards earlier
  prioritized-list alternatives (:1059 computeScore).
- ``staging/src/k8s.io/dynamic-resource-allocation/structured/allocator.go``:
  the exact device allocator (selectors, ExactCount/All, matchAttribute
  constraints, firstAvailable).

TPU-native split — the design insight is that the perf-critical shape
(claim templates stamping identical single-device claims over node-local
pools, ``dra/performance-config.yaml``) is *exactly* a resource-fit
problem, so it folds into the machinery the engines already capacity-couple:

1. **Dense pools** (device path): a distinct (deviceClass, selector-set)
   over node-local interchangeable devices interns to a *pool column*
   appended to the batch's resource axis. Node capacity = matching devices
   on the node's slices; node "requested" = already-allocated matching
   devices; pod request = claim count. The greedy scan / batched rounds then
   enforce in-batch device contention exactly like CPU/memory — no new
   kernel.
2. **Host claims** (everything dense can't express): All-mode, constraints,
   prioritized lists, and network-attached devices get a per-spec
   ``(N,)`` feasibility mask from the exact host allocator (evaluated once
   per distinct claim spec, not per pod). In-batch conflicts on these are
   resolved optimistically: Reserve re-runs the exact allocator against the
   live cache and a losing pod is forgotten + requeued (the reference's
   assume-then-fail path), converging next cycle.

Known deviation: preemption's victim search does not model freed devices
(a victim's claim deallocates via its delete event, next cycle); the
reference's DRA PostFilter special-case (:923) is likewise out of the
dry-run kernel's scope.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, replace
from typing import Iterable, Sequence

import numpy as np

from ..api import types as t

# --------------------------------------------------------------------------
# CEL subset
# --------------------------------------------------------------------------


class CelUnsupportedError(ValueError):
    """Raised for CEL device selectors outside the structured subset —
    surfaced as a claim/class validation failure (the reference fails the
    claim on CEL compile errors, dynamicresources.go:668)."""


# one comparison term: device.driver or device.attributes["..."](.name)?
_DRIVER_RE = re.compile(
    r'^device\.driver\s*(==|!=)\s*"([^"]*)"$'
)
_ATTR_RE = re.compile(
    r'^device\.attributes\["([^"\]]+)"\](?:\.([A-Za-z_]\w*))?'
    r'\s*(==|!=|>=|<=|>|<)\s*(.+)$'
)
_CAP_RE = re.compile(
    r'^device\.capacity\["([^"\]]+)"\](?:\.([A-Za-z_]\w*))?'
    r'\s*(==|!=|>=|<=|>|<)\s*(.+)$'
)


def _parse_literal(text: str):
    text = text.strip()
    if text.startswith('"') and text.endswith('"') and len(text) >= 2:
        return text[1:-1]
    if text in ("true", "false"):
        return text == "true"
    try:
        return int(text)
    except ValueError:
        raise CelUnsupportedError(f"unsupported CEL literal: {text!r}")


def parse_cel(expression: str) -> tuple[tuple[str, str, str, object], ...]:
    """Parse the structured subset: conjunctions (&&) of comparisons on
    ``device.driver``, ``device.attributes["qualified.name"]`` (optionally
    ``["domain"].name``) and ``device.capacity[...]``. Returns canonical
    terms ``(field, key, op, literal)``; raises CelUnsupportedError
    otherwise."""
    terms: list[tuple[str, str, str, object]] = []
    for part in expression.split("&&"):
        part = part.strip()
        if part.startswith("(") and part.endswith(")"):
            part = part[1:-1].strip()
        m = _DRIVER_RE.match(part)
        if m:
            terms.append(("driver", "", m.group(1), m.group(2)))
            continue
        m = _ATTR_RE.match(part)
        if m:
            dom, name, op, lit = m.groups()
            key = f"{dom}.{name}" if name else dom
            terms.append(("attr", key, op, _parse_literal(lit)))
            continue
        m = _CAP_RE.match(part)
        if m:
            dom, name, op, lit = m.groups()
            key = f"{dom}.{name}" if name else dom
            terms.append(("cap", key, op, _parse_literal(lit)))
            continue
        raise CelUnsupportedError(
            f"CEL expression outside the structured subset: {part!r}"
        )
    return tuple(terms)


def _cmp(op: str, a, b) -> bool:
    if op == "==":
        return a == b
    if op == "!=":
        return a != b
    try:
        if op == ">=":
            return a >= b
        if op == "<=":
            return a <= b
        if op == ">":
            return a > b
        if op == "<":
            return a < b
    except TypeError:
        return False
    return False


def _device_matches(
    terms: Iterable[tuple[str, str, str, object]],
    driver: str,
    device: t.Device,
) -> bool:
    attrs = None
    caps = None
    for field, key, op, lit in terms:
        if field == "driver":
            if not _cmp(op, driver, lit):
                return False
        elif field == "attr":
            if attrs is None:
                attrs = dict(device.attributes)
            val = attrs.get(key)
            if val is None or not _cmp(op, val, lit):
                # missing attribute: a CEL runtime error excludes the device
                return False
        else:  # cap
            if caps is None:
                caps = dict(device.capacity)
            val = caps.get(key)
            if val is None or not _cmp(op, val, lit):
                return False
    return True


def _selector_sig(selectors: Sequence[t.CELSelector]) -> tuple:
    """Canonical, hashable signature of a selector list (parsed terms).
    Raises CelUnsupportedError for unparseable expressions."""
    out = []
    for sel in selectors:
        out.extend(parse_cel(sel.expression))
    return tuple(sorted(out, key=repr))


# --------------------------------------------------------------------------
# The cache-resident index
# --------------------------------------------------------------------------

_DevKey = tuple[str, str, str]  # (driver, pool, device name)


@dataclass
class _Pool:
    """One interned dense pool: a deviceClass plus extra request selectors."""

    class_name: str
    extra_terms: tuple
    gen: int = -1                 # generation the caches below were built at
    dense_ok: bool = True         # False once a matching network device seen
    valid: bool = True            # False when the class is missing/bad CEL
    cap: dict[str, int] | None = None     # node -> matching device count
    alloc: dict[str, int] | None = None   # node -> allocated matching count


class DraIndex:
    """Single-owner (scheduler loop thread) DRA state: the class/slice/claim
    listers plus the pool interner and allocated-device bookkeeping. Lives on
    the Cache; snapshots share the reference (encode and Reserve both run on
    the loop thread, like the volume listers)."""

    def __init__(self) -> None:
        self.device_classes: dict[str, t.DeviceClass] = {}
        self.slices: dict[str, t.ResourceSlice] = {}
        self.claims: dict[str, t.ResourceClaim] = {}
        self.generation = 0          # bumped on slice/class topology changes
        # bumped on claim add/remove/update — cheap change signal for the
        # pipelined scheduler's staleness check (claim churn must not
        # invalidate the pool catalogs the way `generation` does)
        self.claims_version = 0
        self._class_terms: dict[str, tuple | None] = {}  # None = bad CEL
        self._pool_ids: dict[tuple, int] = {}
        self._pools: list[_Pool] = []
        # (gen, {(driver,pool,dev): (node_name|'', all_nodes, node_sel, Device, driver)})
        self._catalog: tuple[int, dict] | None = None
        self.allocated_devices: dict[str, set[_DevKey]] = {}  # node ('' = net)

    # ---- listers / mutators ---------------------------------------------
    def add_class(self, dc: t.DeviceClass) -> None:
        self.device_classes[dc.name] = dc
        self._class_terms.pop(dc.name, None)
        self.generation += 1

    def remove_class(self, name: str) -> None:
        if self.device_classes.pop(name, None) is not None:
            self._class_terms.pop(name, None)
            self.generation += 1

    def add_slice(self, sl: t.ResourceSlice) -> None:
        self.slices[sl.name] = sl
        self.generation += 1

    def remove_slice(self, name: str) -> None:
        if self.slices.pop(name, None) is not None:
            self.generation += 1

    def add_claim(self, claim: t.ResourceClaim) -> None:
        old = self.claims.get(claim.key)
        self.claims[claim.key] = claim
        self.claims_version += 1
        self._reconcile_allocation(old, claim)

    def remove_claim(self, key: str) -> None:
        old = self.claims.pop(key, None)
        if old is not None:
            self.claims_version += 1
            self._reconcile_allocation(old, None)

    # ---- allocation bookkeeping -----------------------------------------
    def _reconcile_allocation(
        self, old: t.ResourceClaim | None, new: t.ResourceClaim | None
    ) -> None:
        old_a = old.allocation if old is not None else None
        new_a = new.allocation if new is not None else None
        if old_a is new_a or (old_a == new_a):
            return
        if old_a is not None:
            self._release(old_a)
        if new_a is not None:
            self._consume(new_a)

    def _dev_keys(self, alloc: t.ClaimAllocation) -> list[_DevKey]:
        return [(r.driver, r.pool, r.device) for r in alloc.results]

    def _home(self, key: _DevKey, cat: dict, fallback: str) -> str:
        """Accounting bucket for a device: its slice's node for node-local
        devices, '' (global) for network-attached ones — a network device
        consumed from one node is unavailable from EVERY node."""
        entry = cat.get(key)
        if entry is None:
            return fallback
        node = entry[0]
        return node if node else ""

    def _consume(self, alloc: t.ClaimAllocation) -> None:
        cat = self._ensure_catalog()
        for key in self._dev_keys(alloc):
            bucket = self._home(key, cat, alloc.node_name)
            s = self.allocated_devices.setdefault(bucket, set())
            if key in s:
                continue
            s.add(key)
            self._charge_pools(bucket, key, cat, +1)

    def _release(self, alloc: t.ClaimAllocation) -> None:
        cat = self._ensure_catalog()
        for key in self._dev_keys(alloc):
            bucket = self._home(key, cat, alloc.node_name)
            s = self.allocated_devices.get(bucket)
            if s is not None and key in s:
                s.discard(key)
                self._charge_pools(bucket, key, cat, -1)
                if not s:
                    self.allocated_devices.pop(bucket, None)

    def _charge_pools(self, node: str, key: _DevKey, cat: dict, delta: int) -> None:
        """Keep already-built pool alloc counts incremental (stale pools
        rebuild from scratch on demand, so only current-gen pools matter)."""
        entry = cat.get(key)
        if entry is None:
            return
        _node, _all, _sel, device, driver = entry
        for pool in self._pools:
            if pool.gen != self.generation or pool.alloc is None:
                continue
            if self._pool_device_matches(pool, driver, device):
                pool.alloc[node] = pool.alloc.get(node, 0) + delta
                if pool.alloc[node] <= 0:
                    pool.alloc.pop(node, None)

    # ---- pool interning / evaluation ------------------------------------
    def class_terms(self, name: str) -> tuple | None:
        """Parsed selector terms for a class; None when the class is missing
        or its CEL is outside the subset (claim then blocks, :668)."""
        if name in self._class_terms:
            return self._class_terms[name]
        dc = self.device_classes.get(name)
        terms: tuple | None
        if dc is None:
            return None  # missing class is not cached — it may appear later
        try:
            terms = _selector_sig(dc.selectors)
        except CelUnsupportedError:
            terms = None
        self._class_terms[name] = terms
        return terms

    def intern_pool(
        self, class_name: str, selectors: Sequence[t.CELSelector]
    ) -> int:
        """Pool id for (deviceClass, request selectors); stable across the
        index's lifetime so the batch resource axis stays cycle-stable."""
        try:
            extra = _selector_sig(selectors)
        except CelUnsupportedError:
            extra = None
        key = (class_name, extra)
        pid = self._pool_ids.get(key)
        if pid is None:
            pid = len(self._pools)
            self._pool_ids[key] = pid
            # extra_terms None = unparseable request CEL: the pool stays
            # permanently invalid (ensure_pool re-derives valid from it, so
            # the marker must survive interning)
            self._pools.append(
                _Pool(class_name=class_name, extra_terms=extra)
            )
        return pid

    def _pool_device_matches(
        self, pool: _Pool, driver: str, device: t.Device
    ) -> bool:
        if pool.extra_terms is None:
            return False   # unparseable request CEL — matches nothing
        cls_terms = self.class_terms(pool.class_name)
        if cls_terms is None:
            return False
        return _device_matches(cls_terms, driver, device) and _device_matches(
            pool.extra_terms, driver, device
        )

    def _ensure_catalog(self) -> dict:
        if self._catalog is not None and self._catalog[0] == self.generation:
            return self._catalog[1]
        cat: dict = {}
        for sl in self.slices.values():
            for dev in sl.devices:
                cat[(sl.driver, sl.pool, dev.name)] = (
                    sl.node_name, sl.all_nodes, sl.node_selector, dev, sl.driver
                )
        self._catalog = (self.generation, cat)
        self._rebucket(cat)
        return cat

    def _rebucket(self, cat: dict) -> None:
        """Claims can be observed before their slices (informer start order
        is best-effort; a relist can interleave kinds): a device consumed
        against an empty catalog lands in the claim's ``node_name`` bucket.
        On every catalog regeneration, re-derive each allocated device's
        home so network-attached devices migrate to the global ``''``
        bucket — otherwise other nodes still see the device free (double
        allocation) and a later ``_release`` misses the stale entry,
        leaking it as permanently allocated."""
        moved: dict[str, set[_DevKey]] = {}
        for bucket, keys in self.allocated_devices.items():
            for key in keys:
                home = self._home(key, cat, bucket)
                moved.setdefault(home, set()).add(key)
        self.allocated_devices = {b: s for b, s in moved.items() if s}

    def ensure_pool(self, pid: int) -> _Pool:
        pool = self._pools[pid]
        if pool.gen == self.generation:
            return pool
        pool.valid = (
            pool.extra_terms is not None
            and self.class_terms(pool.class_name) is not None
            and pool.class_name in self.device_classes
        )
        cap: dict[str, int] = {}
        dense_ok = True
        cat = self._ensure_catalog()
        if pool.valid:
            for (driver, _p, _d), entry in cat.items():
                node, all_nodes, node_sel, device, drv = entry
                if not self._pool_device_matches(pool, drv, device):
                    continue
                if all_nodes or node_sel is not None or not node:
                    dense_ok = False
                    continue
                cap[node] = cap.get(node, 0) + 1
        alloc: dict[str, int] = {}
        for node, keys in self.allocated_devices.items():
            for key in keys:
                entry = cat.get(key)
                if entry is None:
                    continue
                if self._pool_device_matches(pool, entry[4], entry[3]):
                    alloc[node] = alloc.get(node, 0) + 1
        pool.cap = cap
        pool.alloc = alloc
        pool.dense_ok = dense_ok
        pool.gen = self.generation
        return pool

    # ---- exact host allocator -------------------------------------------
    def node_free_devices(
        self, node_name: str, node_labels: dict | None = None,
        taken: set[_DevKey] | None = None,
    ) -> list[tuple[_DevKey, str, t.Device]]:
        """Free concrete devices usable from ``node_name``: the node's local
        slices plus all-nodes / matching node-selector slices, minus
        allocated devices (node-pinned and network), minus ``taken``.
        Deterministic order (sorted key)."""
        from ..state.volumes import node_affinity_matches

        cat = self._ensure_catalog()
        allocated: set[_DevKey] = set()
        allocated.update(self.allocated_devices.get(node_name, ()))
        allocated.update(self.allocated_devices.get("", ()))
        if taken:
            allocated.update(taken)
        out = []
        for key in sorted(cat):
            node, all_nodes, node_sel, device, driver = cat[key]
            if key in allocated:
                continue
            if node:
                if node != node_name:
                    continue
            elif all_nodes:
                pass
            elif node_sel is not None:
                if not node_affinity_matches(
                    node_sel, node_labels or {}, node_name
                ):
                    continue
            else:
                continue
            out.append((key, driver, device))
        return out

    def allocate_on_node(
        self,
        claims: Sequence[t.ResourceClaim],
        node_name: str,
        node_labels: dict | None = None,
    ) -> list[t.ClaimAllocation] | None:
        """The structured allocator (allocator.go semantics, deterministic
        first-fit): try to satisfy every claim's requests from the node's
        free devices. Returns one ClaimAllocation per claim or None."""
        free = self.node_free_devices(node_name, node_labels)
        taken: set[_DevKey] = set()
        allocations: list[t.ClaimAllocation] = []
        for claim in claims:
            results = self._allocate_claim(claim, node_name, free, taken)
            if results is None:
                return None
            allocations.append(
                t.ClaimAllocation(node_name=node_name, results=tuple(results))
            )
        return allocations

    def _candidates(
        self, class_name: str, selectors, free, taken: set[_DevKey]
    ) -> list[tuple[_DevKey, str, t.Device]] | None:
        cls_terms = self.class_terms(class_name)
        if cls_terms is None or class_name not in self.device_classes:
            return None
        try:
            extra = _selector_sig(selectors)
        except CelUnsupportedError:
            return None
        return [
            (key, driver, dev)
            for key, driver, dev in free
            if key not in taken
            and _device_matches(cls_terms, driver, dev)
            and _device_matches(extra, driver, dev)
        ]

    def _allocate_claim(
        self,
        claim: t.ResourceClaim,
        node_name: str,
        free,
        taken: set[_DevKey],
    ) -> list[t.DeviceResult] | None:
        """Allocate one claim; on success, consumed keys join ``taken``.
        Constraints (matchAttribute) retry over candidate attribute values,
        smallest value first, matching the allocator's deterministic
        backtracking."""
        constraint_attrs = [
            (c.match_attribute, set(c.requests)) for c in claim.constraints
        ]

        def pick(attr_pin: dict[str, object]) -> list[t.DeviceResult] | None:
            picked: list[t.DeviceResult] = []
            local_taken: set[_DevKey] = set()

            def req_candidates(names, class_name, selectors):
                """``names``: every name this request answers to for
                constraint membership — the parent request name AND (for a
                prioritized-list alternative) the "parent/sub" form, per
                resource.k8s.io/v1: a constraint naming the main request
                covers its subrequests."""
                cands = self._candidates(class_name, selectors, free, taken)
                if cands is None:
                    return None
                out = []
                for key, driver, dev in cands:
                    if key in local_taken:
                        continue
                    ok = True
                    for attr, reqs in constraint_attrs:
                        if reqs and reqs.isdisjoint(names):
                            continue
                        pin = attr_pin.get(attr)
                        if pin is not None and dev.attributes_dict().get(attr) != pin:
                            ok = False
                            break
                    if ok:
                        out.append((key, driver, dev))
                return out

            def take(req_name, cands, count, all_devices) -> bool:
                if all_devices:
                    if not cands:
                        return False
                    chosen = cands
                else:
                    if len(cands) < count:
                        return False
                    chosen = cands[:count]
                for key, driver, dev in chosen:
                    local_taken.add(key)
                    picked.append(t.DeviceResult(
                        request=req_name, driver=key[0], pool=key[1],
                        device=key[2],
                    ))
                return True

            for req in claim.requests:
                if req.first_available:
                    done = False
                    for i, sub in enumerate(req.first_available):
                        full = f"{req.name}/{sub.name}"
                        cands = req_candidates(
                            {req.name, full},
                            sub.device_class_name, sub.selectors,
                        )
                        if cands and take(full, cands, sub.count, False):
                            done = True
                            break
                    if not done:
                        return None
                else:
                    cands = req_candidates(
                        {req.name}, req.device_class_name, req.selectors
                    )
                    if cands is None or not take(
                        req.name, cands, req.count, req.all_devices
                    ):
                        return None
            taken.update(local_taken)
            return picked

        if not constraint_attrs:
            return pick({})
        # matchAttribute backtracking: each constrained attribute pins
        # INDEPENDENTLY to one of its observed values; try the product of
        # value choices, sorted for determinism, first full assignment wins
        # (allocator.go's per-constraint backtracking)
        import itertools

        attrs = sorted({a for a, _ in constraint_attrs})
        per_attr_values: list[list[object]] = []
        for attr in attrs:
            seen: set[str] = set()
            values: list[object] = []
            for key, driver, dev in free:
                if key in taken:
                    continue
                v = dev.attributes_dict().get(attr)
                if v is not None and repr(v) not in seen:
                    seen.add(repr(v))
                    values.append(v)
            if not values:
                return None
            per_attr_values.append(sorted(values, key=repr))
        for combo in itertools.product(*per_attr_values):
            res = pick(dict(zip(attrs, combo)))
            if res is not None:
                return res
        return None

    # ---- claim status transitions (Reserve / Unreserve / informers) -----
    def set_allocation(
        self, key: str, alloc: t.ClaimAllocation, pod_uid: str
    ) -> None:
        claim = self.claims[key]
        new = replace(
            claim, allocation=alloc,
            reserved_for=claim.reserved_for + (pod_uid,),
        )
        self.claims[key] = new
        self._reconcile_allocation(claim, new)

    def clear_allocation(self, key: str) -> None:
        claim = self.claims.get(key)
        if claim is None or claim.allocation is None:
            return
        new = replace(claim, allocation=None, reserved_for=())
        self.claims[key] = new
        self._reconcile_allocation(claim, new)

    def release_claim(self, key: str, pod_uid: str) -> bool:
        """Unreserve semantics for a pod that triggered the allocation: drop
        the pod's reservedFor entry; deallocate only when NO other pod still
        holds a reservation (another sharer may have reserved the same claim
        this cycle — its allocation must survive). Returns True when the
        claim was actually deallocated."""
        claim = self.claims.get(key)
        if claim is None:
            return False
        remaining = tuple(u for u in claim.reserved_for if u != pod_uid)
        if remaining:
            self.claims[key] = replace(claim, reserved_for=remaining)
            return False
        if claim.allocation is None:
            if remaining != claim.reserved_for:
                self.claims[key] = replace(claim, reserved_for=remaining)
            return False
        new = replace(claim, allocation=None, reserved_for=())
        self.claims[key] = new
        self._reconcile_allocation(claim, new)
        return True

    def add_reserved(self, key: str, pod_uid: str) -> None:
        claim = self.claims.get(key)
        if claim is not None and pod_uid not in claim.reserved_for:
            self.claims[key] = replace(
                claim, reserved_for=claim.reserved_for + (pod_uid,)
            )

    def remove_reserved(self, key: str, pod_uid: str) -> None:
        claim = self.claims.get(key)
        if claim is not None and pod_uid in claim.reserved_for:
            self.claims[key] = replace(
                claim,
                reserved_for=tuple(
                    u for u in claim.reserved_for if u != pod_uid
                ),
            )


# --------------------------------------------------------------------------
# Per-encode view
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class PodDra:
    """Per-pod DRA analysis, all hashable (joins the encoder's signature
    machinery)."""

    blocked: bool = False
    # rejected-by reason for PreEnqueue-style waits ('' = schedulable path)
    pin: str | None = None            # node the pod must land on (allocated)
    dense: tuple[tuple[int, int], ...] = ()   # (pool id, count)
    host_specs: tuple = ()            # claim-spec sigs needing host masks

    @property
    def sig(self) -> tuple:
        return (self.blocked, self.pin, self.dense, self.host_specs)

    @property
    def any_work(self) -> bool:
        return (
            self.blocked or self.pin is not None
            or bool(self.dense) or bool(self.host_specs)
        )


def _claim_spec_sig(claim: t.ResourceClaim) -> tuple:
    return (claim.requests, claim.constraints)


class DraState:
    """Per-encode DRA view (the VolumeState analog): resolves pods' claims
    into dense pool requests + static contributions, and computes host-path
    feasibility masks once per distinct claim spec."""

    def __init__(self, snapshot) -> None:
        self.index: DraIndex = snapshot.dra
        self.snapshot = snapshot
        self._pod_cache: dict[tuple, PodDra] = {}
        self._spec_masks: dict[tuple, np.ndarray] = {}
        self._spec_scores: dict[tuple, np.ndarray | None] = {}
        self.used_pools: set[int] = set()

    # ---- analysis --------------------------------------------------------
    def analyze(self, pod: t.Pod) -> PodDra:
        claim_keys = tuple(
            f"{pod.namespace}/{rc.claim_name}"
            for rc in pod.resource_claims if rc.claim_name
        )
        if not claim_keys:
            return PodDra()
        cache_key = (claim_keys, pod.uid)
        got = self._pod_cache.get(cache_key)
        if got is not None:
            return got
        idx = self.index
        pins: set[str] = set()
        dense: dict[int, int] = {}
        host: list[tuple] = []
        blocked = False
        for key in claim_keys:
            claim = idx.claims.get(key)
            if claim is None:
                blocked = True            # PreEnqueue: claim not created yet
                break
            if claim.allocation is not None:
                if (
                    pod.uid not in claim.reserved_for
                    and len(claim.reserved_for) >= t.RESERVED_FOR_MAX
                ):
                    blocked = True
                    break
                if claim.allocation.node_name:
                    pins.add(claim.allocation.node_name)
                continue
            spec_dense = self._spec_dense(claim)
            if spec_dense is None:
                host.append(_claim_spec_sig(claim))
            elif spec_dense == "blocked":
                blocked = True
                break
            else:
                for pid, count in spec_dense:
                    dense[pid] = dense.get(pid, 0) + count
                    self.used_pools.add(pid)
        if len(pins) > 1:
            blocked = True
        res = PodDra(
            blocked=blocked,
            pin=(next(iter(pins)) if pins and not blocked else None),
            dense=tuple(sorted(dense.items())) if not blocked else (),
            host_specs=tuple(host) if not blocked else (),
        )
        self._pod_cache[cache_key] = res
        return res

    def _spec_dense(self, claim: t.ResourceClaim):
        """Dense pool items for a claim spec, or None (host path) or
        'blocked' (invalid class / bad CEL, :668 validateDeviceClass)."""
        if claim.constraints:
            return None
        items: list[tuple[int, int]] = []
        for req in claim.requests:
            if req.first_available or req.all_devices:
                return None
            if not req.device_class_name:
                return "blocked"
            if idx_terms_invalid(self.index, req.device_class_name):
                return "blocked"
            pid = self.index.intern_pool(req.device_class_name, req.selectors)
            pool = self.index.ensure_pool(pid)
            if not pool.valid:
                return "blocked"
            if not pool.dense_ok:
                return None
            items.append((pid, req.count))
        return items

    # ---- host-path masks / scores ---------------------------------------
    def _node_labels(self, nt) -> list[dict]:
        return [info.node.labels_dict() for info in nt.infos]

    def spec_mask(self, spec_sig: tuple, nt) -> np.ndarray:
        """(N,) bool: nodes where the exact allocator can place a claim with
        this spec against the CURRENT allocations (no in-batch coupling —
        Reserve re-verifies)."""
        m = self._spec_masks.get(spec_sig)
        if m is not None:
            return m
        requests, constraints = spec_sig
        probe = t.ResourceClaim(
            name="?", requests=requests, constraints=constraints
        )
        N = nt.num_nodes
        m = np.zeros(N, dtype=bool)
        labels = self._node_labels(nt)
        for i, name in enumerate(nt.node_names):
            if self.index.allocate_on_node([probe], name, labels[i]) is not None:
                m[i] = True
        self._spec_masks[spec_sig] = m
        return m

    def spec_score(self, spec_sig: tuple, nt) -> np.ndarray | None:
        """(N,) int64 prioritized-list raw score (computeScore :1087):
        Σ over firstAvailable requests of (FIRST_AVAILABLE_MAX - chosen
        alternative index) on each feasible node. None when the spec has no
        prioritized lists."""
        if spec_sig in self._spec_scores:
            return self._spec_scores[spec_sig]
        requests, constraints = spec_sig
        if not any(r.first_available for r in requests):
            self._spec_scores[spec_sig] = None
            return None
        probe = t.ResourceClaim(
            name="?", requests=requests, constraints=constraints
        )
        N = nt.num_nodes
        out = np.zeros(N, dtype=np.int64)
        labels = self._node_labels(nt)
        for i, name in enumerate(nt.node_names):
            allocs = self.index.allocate_on_node([probe], name, labels[i])
            if allocs is None:
                continue
            chosen = {r.request for r in allocs[0].results}
            s = 0
            for req in requests:
                for j, sub in enumerate(req.first_available):
                    if f"{req.name}/{sub.name}" in chosen:
                        s += t.FIRST_AVAILABLE_MAX - j
                        break
            out[i] = s
        self._spec_scores[spec_sig] = out
        return out

    # ---- dense columns ---------------------------------------------------
    def pool_columns(self) -> list[int]:
        """Stable column order for this batch's dense pools."""
        return sorted(self.used_pools)

    def pool_resource_names(self) -> list[str]:
        return [f"dra/pool{pid}" for pid in self.pool_columns()]

    def fill_node_columns(self, nt, first_col: int) -> None:
        """Write pool capacity/allocated into the node tensors' appended
        columns (cheap per cycle: O(nodes-with-devices), overwritten
        unconditionally so incremental row reuse stays correct)."""
        name_to_idx = {n: i for i, n in enumerate(nt.node_names)}
        for j, pid in enumerate(self.pool_columns()):
            pool = self.index.ensure_pool(pid)
            col = first_col + j
            nt.alloc[:, col] = 0
            nt.requested[:, col] = 0
            nt.nonzero_requested[:, col] = 0
            for node, cap in (pool.cap or {}).items():
                i = name_to_idx.get(node)
                if i is not None:
                    nt.alloc[i, col] = cap
            for node, used in (pool.alloc or {}).items():
                i = name_to_idx.get(node)
                if i is not None:
                    nt.requested[i, col] = used
                    nt.nonzero_requested[i, col] = used


def idx_terms_invalid(index: DraIndex, class_name: str) -> bool:
    """True when the class exists but its CEL is unparseable (permanently
    blocked); a *missing* class is handled as blocked-until-add upstream."""
    if class_name not in index.device_classes:
        return False
    return index.class_terms(class_name) is None
