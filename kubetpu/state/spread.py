"""PodTopologySpread tensorization.

Reference: pkg/scheduler/framework/plugins/podtopologyspread/
- common.go:87 filterTopologySpreadConstraints (constraint extraction,
  minDomains default 1, NodeAffinityPolicy default Honor, NodeTaintsPolicy
  default Ignore, matchLabelKeys merged into the selector)
- filtering.go:237 calPreFilterState (per-domain match counts over eligible
  nodes), :314 Filter (skew = matchNum + selfMatch − minMatch ≤ maxSkew;
  nodes missing the topology key are UnschedulableAndUnresolvable)
- scoring.go:61 initPreScoreState / :118 PreScore (domain counts +
  log-normalizing weight), :199 Score, :229 NormalizeScore

Batch encoding: distinct *constraint signatures* across the pending batch are
interned — a signature is (topology key, selector, namespace, the pod's full
topology-key set, the pod's required-affinity signature, inclusion policies,
tolerations when taints policy is Honor) — because per-domain counts depend on
all of these but on nothing else about the pod. Per signature we precompute:

- ``eligible (N,)``: the node participates in counting (calPreFilterState's
  processNode guards: required affinity match under Honor, untolerated
  NoSchedule/NoExecute taint under Honor, ALL of the pod's topology keys
  present on the node).
- ``node_domain (N,)``: interned id of the node's topology value among the
  domains of eligible nodes; −1 when the node is ineligible or its value is
  not a counted domain (Go's map lookup then yields matchNum 0).
- ``node_count (N,)``: matching existing pods per node (countPodsMatchSelector:
  same namespace, selector match; terminating pods skipped). This, not the
  per-domain sum, is the scan's carried state — in-batch assignments scatter
  +1 into it (updateWithPod semantics) and per-domain sums are segment-summed
  on device.
- ``has_key (N,)``: the node carries this constraint's topology key.
- ``num_domains``: |counted domains| (static: in-batch updates can only touch
  domains of eligible nodes, which are all pre-counted).

Pod side: per (pod, constraint-slot): signature index, action (hard/soft),
max_skew, min_domains, self_match, is_hostname; plus ``pod_match_sig (P, S)``
(does pending pod p match signature s's selector+namespace — drives the
in-batch count updates) and ``ignored (P, N)`` for scoring (node missing any
of the pod's soft topology keys → score 0, scoring.go:90).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from ..api import selectors as sel
from ..api import types as t
from .encoder import NodeTensors
from .vocab import Vocab

HOSTNAME_KEY = "kubernetes.io/hostname"

HARD = 0
SOFT = 1


def _affinity_sig(pod: t.Pod):
    na = pod.affinity.node_affinity if pod.affinity else None
    return (pod.node_selector, na.required if na else None)


def _required_affinity_mask(nt: NodeTensors, pod: t.Pod) -> np.ndarray:
    """GetRequiredNodeAffinity(pod).Match — nodeSelector AND required node
    affinity (component-helpers/scheduling/corev1/nodeaffinity)."""
    m = np.ones(nt.num_nodes, dtype=bool)
    for k, v in pod.node_selector:
        m &= nt.requirement_mask(t.Requirement(k, t.Operator.IN, (v,)))
    na = pod.affinity.node_affinity if pod.affinity else None
    if na and na.required is not None:
        m &= nt.node_selector_mask(na.required)
    return m


def _selector_matches(selector: t.LabelSelector | None, labels: dict) -> bool:
    """Selector.Matches semantics: nil selector (labels.Nothing) matches
    nothing, empty selector (labels.Everything) matches everything. Used for
    selfMatch (filtering.go:346)."""
    if selector is None:
        return False
    return sel.label_selector_matches(selector, labels)


def _selector_counts(selector: t.LabelSelector | None, labels: dict) -> bool:
    """countPodsMatchSelector semantics (common.go:145): an EMPTY selector
    counts no pods (`selector.Empty() → 0`), unlike Matches."""
    if selector is None:
        return False
    if not selector.match_labels and not selector.match_expressions:
        return False
    return sel.label_selector_matches(selector, labels)


@dataclass
class SpreadTensors:
    """Numpy-side spread encoding. ``None`` when no pod has constraints."""

    # per-signature (S = #distinct signatures, N node capacity, D = max domains)
    eligible: np.ndarray       # (S, N) bool
    node_domain: np.ndarray    # (S, N) int32, -1 = not a counted domain
    node_count: np.ndarray     # (S, N) int32 — matching pods per node
    has_key: np.ndarray        # (S, N) bool
    domain_present: np.ndarray # (S, D) bool
    num_domains: np.ndarray    # (S,) int32
    is_hostname: np.ndarray    # (S,) bool
    # per (pod, constraint-slot) (P pods, C = max constraints per pod)
    sig_idx: np.ndarray        # (P, C) int32, -1 = unused slot
    action: np.ndarray         # (P, C) int8 HARD/SOFT
    max_skew: np.ndarray       # (P, C) int32
    min_domains: np.ndarray    # (P, C) int32
    self_match: np.ndarray     # (P, C) int32 0/1
    # scoring helpers
    pod_match_sig: np.ndarray  # (P, S) bool
    ignored: np.ndarray        # (P, N) bool — soft-scoring ignored nodes
    has_hard: bool
    has_soft: bool

    @property
    def num_sigs(self) -> int:
        return self.eligible.shape[0]

    @property
    def max_domains(self) -> int:
        return self.domain_present.shape[1]


def default_selector_from_services(snapshot):
    """component-helpers DefaultSelector, services part: the merged selector
    of every service in the pod's namespace selecting the pod (controllers
    — RC/RS/SS — are not modeled; services are what scheduler_perf's
    DefaultTopologySpreading exercises). None when nothing selects the pod
    (buildDefaultConstraints then drops the defaults, common.go:70)."""
    by_ns: dict[str, list] = {}
    for svc in snapshot.services.values():
        by_ns.setdefault(svc.namespace, []).append(svc)

    def fn(pod: t.Pod):
        labels = pod.labels_dict()
        merged: dict[str, str] = {}
        for svc in by_ns.get(pod.namespace, ()):
            if svc.selector and all(
                labels.get(k) == v for k, v in svc.selector
            ):
                merged.update(dict(svc.selector))
        if not merged:
            return None
        return t.LabelSelector(match_labels=tuple(sorted(merged.items())))

    return fn


def encode_spread(
    nt: NodeTensors,
    pods: Sequence[t.Pod],
    default_constraints: Sequence[t.TopologySpreadConstraint] = (),
    pad_pods: int | None = None,
    default_selector_of=None,
    cache=None,
    groups: dict | None = None,
) -> SpreadTensors | None:
    """Build spread tensors for the batch; None when no pending pod has (or
    inherits) topology spread constraints.

    ``default_constraints`` are only applied to pods WITHOUT their own
    constraints, with the selector computed by ``default_selector_of(pod)``
    — the DefaultSelector derived from owning services/controllers
    (common.go:62 buildDefaultConstraints). A pod whose default selector is
    empty/None gets no constraints, exactly like the reference (common.go's
    ``if selector.Empty() { return nil }``).

    ``groups``: precomputed template groups
    (``encode_cache.collect_pod_groups``); None builds them here. The
    per-node matching-pod counts become one selector verdict per (selector,
    template) — persisted across cycles by ``cache`` (EncodeCache) — plus a
    vector add per matching template, instead of a Python walk over every
    existing pod per signature.
    """
    import dataclasses

    P = len(pods)

    sel_cache: dict = {}

    def effective(p: t.Pod) -> tuple[t.TopologySpreadConstraint, ...]:
        if p.topology_spread_constraints:
            return p.topology_spread_constraints
        if not default_constraints or default_selector_of is None:
            return ()
        key = (p.namespace, p.labels)
        got = sel_cache.get(key)
        if got is None:
            dsel = default_selector_of(p)
            got = (
                ()
                if dsel is None else tuple(
                    dataclasses.replace(c, selector=dsel)
                    for c in default_constraints
                )
            )
            sel_cache[key] = got
        return got

    eff = [effective(p) for p in pods]
    if not any(eff):
        return None
    N = nt.num_nodes
    NC = nt.alloc.shape[0]
    PP = max(pad_pods or P, P)

    from .encode_cache import collapse_label_groups, groups_for, pod_gids_for

    lgroups = collapse_label_groups(groups_for(nt, cache, groups))
    sel_store = cache.sel_counts if cache is not None else None
    local_sel: dict = {}

    # per-pod TEMPLATE ids: the pod-side tensors (constraint slots, soft
    # ignored rows, selector-match rows) are pure functions of the
    # template, computed once per distinct template in the batch
    pod_gid = pod_gids_for(pods, cache)

    sig_vocab = Vocab()
    sig_info: list[dict] = []           # per sig id: everything host-side
    pod_slots: list[list[tuple]] = []   # per pod: (sig id, action, c)

    aff_cache: dict[tuple, np.ndarray] = {}
    tmpl_slots: dict[int, list] = {}
    for p_i, p in enumerate(pods):
        got_slots = tmpl_slots.get(pod_gid[p_i])
        if got_slots is not None:
            pod_slots.append(got_slots)
            continue
        slots: list[tuple] = []
        constraints = eff[p_i]
        if constraints:
            key_set = frozenset(c.topology_key for c in constraints)
            hard_keys = frozenset(
                c.topology_key for c in constraints
                if c.when_unsatisfiable == t.UnsatisfiableConstraintAction.DO_NOT_SCHEDULE
            )
            soft_keys = frozenset(
                c.topology_key for c in constraints
                if c.when_unsatisfiable == t.UnsatisfiableConstraintAction.SCHEDULE_ANYWAY
            )
            for c in constraints:
                hard = (
                    c.when_unsatisfiable
                    == t.UnsatisfiableConstraintAction.DO_NOT_SCHEDULE
                )
                # selector with matchLabelKeys merged (common.go:96-106)
                selector = c.selector or t.LabelSelector()
                if c.match_label_keys:
                    plabels = p.labels_dict()
                    extra = tuple(
                        (k, plabels[k]) for k in c.match_label_keys if k in plabels
                    )
                    if extra:
                        selector = t.LabelSelector(
                            match_labels=tuple(
                                sorted(set(selector.match_labels) | set(extra))
                            ),
                            match_expressions=selector.match_expressions,
                        )
                # Key-set guard: filtering counts over the pod's HARD set
                # (calPreFilterState uses getConstraints = DoNotSchedule);
                # scoring over the SOFT set (initPreScoreState).
                ks = hard_keys if hard else soft_keys
                taints_part = (
                    p.tolerations if c.node_taints_policy == "Honor" else None
                )
                sig = (
                    c.topology_key,
                    selector,
                    p.namespace,
                    ks,
                    _affinity_sig(p) if c.node_affinity_policy == "Honor" else None,
                    c.node_affinity_policy,
                    c.node_taints_policy,
                    taints_part,
                )
                sid = sig_vocab.intern(sig)
                if sid == len(sig_info):
                    sig_info.append(
                        dict(
                            key=c.topology_key,
                            selector=selector,
                            namespace=p.namespace,
                            key_set=ks,
                            pod=p,
                            na_policy=c.node_affinity_policy,
                            taints_policy=c.node_taints_policy,
                            tolerations=p.tolerations,
                        )
                    )
                kwargs_min = c.min_domains if c.min_domains is not None else 1
                self_match = int(
                    _selector_matches(selector, p.labels_dict())
                ) if selector is not None else 0
                slots.append(
                    (sid, HARD if hard else SOFT, c.max_skew, kwargs_min, self_match)
                )
        tmpl_slots[pod_gid[p_i]] = slots
        pod_slots.append(slots)

    S = len(sig_info)
    C = max((len(s) for s in pod_slots), default=1) or 1

    eligible = np.zeros((S, NC), dtype=bool)
    node_domain = np.full((S, NC), -1, dtype=np.int32)
    node_count = np.zeros((S, NC), dtype=np.int32)
    has_key = np.zeros((S, NC), dtype=bool)
    is_hostname = np.zeros(S, dtype=bool)
    domain_vocabs: list[Vocab] = []

    # Per-node matching-pod counts per (selector, namespace): dedupe across sigs.
    count_cache: dict[tuple, np.ndarray] = {}
    # Per-node "no untolerated DoNotSchedule taint" per tolerations tuple.
    taint_cache: dict[tuple, np.ndarray] = {}

    for s_id, info in enumerate(sig_info):
        key = info["key"]
        is_hostname[s_id] = key == HOSTNAME_KEY
        kid_values = nt.topology_values(key)            # (N,) value ids, -1 absent
        has_key[s_id, :N] = kid_values >= 0

        elig = np.ones(N, dtype=bool)
        # all of the pod's (hard|soft) topology keys present
        for k in info["key_set"]:
            elig &= nt.topology_values(k) >= 0
        if info["na_policy"] == "Honor":
            aff_key = _affinity_sig(info["pod"])
            m = aff_cache.get(aff_key)
            if m is None:
                m = _required_affinity_mask(nt, info["pod"])
                aff_cache[aff_key] = m
            elig &= m
        if info["taints_policy"] == "Honor":
            tol = info["tolerations"]
            tm = taint_cache.get(tol)
            if tm is None:
                tm = np.array(
                    [
                        sel.find_untolerated_taint(i.node.taints, tol) is None
                        for i in nt.infos
                    ],
                    dtype=bool,
                )
                taint_cache[tol] = tm
            elig &= tm
        eligible[s_id, :N] = elig

        # Counted domains (filtering.go's TpValueToMatchNum universe) are the
        # values of ELIGIBLE nodes — interned first, so ids < num_counted are
        # exactly the counted domains (domain_present/num_domains below).
        # Values appearing only on ineligible nodes get ids AFTER them: their
        # per-domain sum is structurally 0 (matchNum map-miss → 0,
        # filtering.go:350) but they still count toward the SCORING topology
        # size, which is over filtered nodes' values (scoring.go:99 topoSize).
        dv = Vocab()
        for n_i in range(N):
            if elig[n_i] and kid_values[n_i] >= 0:
                node_domain[s_id, n_i] = dv.intern(int(kid_values[n_i]))
        num_counted = len(dv)
        for n_i in range(N):
            if kid_values[n_i] >= 0 and node_domain[s_id, n_i] < 0:
                node_domain[s_id, n_i] = dv.intern(int(kid_values[n_i]))
        domain_vocabs.append((dv, num_counted))

        ck = (info["selector"], info["namespace"])
        counts = count_cache.get(ck)
        if counts is None:
            counts = np.zeros(N, dtype=np.int64)
            selector, ns = ck
            # countPodsMatchSelector semantics (common.go:145): a nil or
            # EMPTY selector counts nothing — and a non-empty one is
            # evaluated once per TEMPLATE, not per pod
            if selector is not None and (
                selector.match_labels or selector.match_expressions
            ):
                for (labels, ns_g), (vec, ld) in lgroups.items():
                    if ns_g != ns:
                        continue
                    mkey = (selector, labels)
                    ok = (
                        sel_store.get(mkey) if sel_store is not None
                        else local_sel.get(mkey)
                    )
                    if ok is None:
                        ok = sel.label_selector_matches(selector, ld)
                        if sel_store is not None:
                            sel_store.put(mkey, ok)
                        else:
                            local_sel[mkey] = ok
                    if ok:
                        counts = counts + vec
            count_cache[ck] = counts
        # counts participate only on eligible nodes (processNode early-returns)
        node_count[s_id, :N] = np.where(elig, counts, 0)

    D = max((len(v) for v, _ in domain_vocabs), default=1) or 1
    domain_present = np.zeros((S, D), dtype=bool)
    num_domains = np.zeros(S, dtype=np.int32)
    for s_id, (dv, num_counted) in enumerate(domain_vocabs):
        domain_present[s_id, :num_counted] = True
        num_domains[s_id] = num_counted

    sig_idx = np.full((PP, C), -1, dtype=np.int32)
    action = np.zeros((PP, C), dtype=np.int8)
    max_skew = np.ones((PP, C), dtype=np.int32)
    min_domains = np.ones((PP, C), dtype=np.int32)
    self_match = np.zeros((PP, C), dtype=np.int32)
    pod_match_sig = np.zeros((PP, S), dtype=bool)
    ignored = np.zeros((PP, NC), dtype=bool)
    has_hard = has_soft = False
    tmpl_rows: dict[int, tuple] = {}
    for i, slots in enumerate(pod_slots):
        ent = tmpl_rows.get(pod_gid[i])
        if ent is None:
            soft_keys = [
                c.topology_key
                for c in eff[i]
                if c.when_unsatisfiable
                == t.UnsatisfiableConstraintAction.SCHEDULE_ANYWAY
            ]
            ig = None
            if soft_keys:
                ig = np.zeros(N, dtype=bool)
                for k in soft_keys:
                    ig |= nt.topology_values(k) < 0
            pod = pods[i]
            match_row = np.zeros(S, dtype=bool)
            for s_id, info in enumerate(sig_info):
                # counting semantics, not Matches: a batch-assigned pod
                # changes the counts exactly as a from-scratch
                # calPreFilterState would
                if pod.namespace == info["namespace"] and _selector_counts(
                    info["selector"], pod.labels_dict()
                ):
                    match_row[s_id] = True
            ent = (ig, match_row)
            tmpl_rows[pod_gid[i]] = ent
        ig, match_row = ent
        if ig is not None:
            ignored[i, :N] = ig
        pod_match_sig[i, :S] = match_row
        for c_i, (sid, act, skew, mind, selfm) in enumerate(slots):
            sig_idx[i, c_i] = sid
            action[i, c_i] = act
            max_skew[i, c_i] = skew
            min_domains[i, c_i] = mind
            self_match[i, c_i] = selfm
            has_hard = has_hard or act == HARD
            has_soft = has_soft or act == SOFT

    return SpreadTensors(
        eligible=eligible,
        node_domain=node_domain,
        node_count=node_count,
        has_key=has_key,
        domain_present=domain_present,
        num_domains=num_domains,
        is_hostname=is_hostname,
        sig_idx=sig_idx,
        action=action,
        max_skew=max_skew,
        min_domains=min_domains,
        self_match=self_match,
        pod_match_sig=pod_match_sig,
        ignored=ignored,
        has_hard=has_hard,
        has_soft=has_soft,
    )
