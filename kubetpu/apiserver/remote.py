"""RemoteStore — the store protocol over the API server's REST + watch.

The client-go side of the process boundary: a RemoteStore exposes the SAME
surface the in-process MemStore does (get/list/create/update/delete/watch),
so ``Reflector``/``SchedulerInformers``/``StoreClient`` and every
controller run unchanged against a remote API server — scheduler and
control plane in separate processes, exactly the reference's deployment
shape (components talk only to the apiserver, SURVEY §1).

Watch is the pull form: ``RemoteWatcher.poll`` GETs
``?watch=1&resourceVersion=<cursor>`` with a short long-poll; HTTP 410 maps
back to ``CompactedError`` so the reflector's relist path fires.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any

from ..api import scheme
from ..store.memstore import CompactedError, ConflictError, WatchEvent


class RemoteStoreError(Exception):
    pass


class RemoteUnavailableError(ConnectionError):
    """Transient transport failure (connection refused/reset, timeout):
    derives from ConnectionError so pump loops can catch-and-retry it the
    way client-go's ListAndWatch retries — one apiserver restart must not
    kill a component process."""


class RemoteStore:
    def __init__(self, base_url: str, timeout_s: float = 30.0) -> None:
        self.base = base_url.rstrip("/")
        self.timeout_s = timeout_s

    # ------------------------------------------------------------ plumbing
    def _request(self, method: str, path: str, body: dict | None = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            f"{self.base}{path}", data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            payload = {}
            try:
                payload = json.loads(e.read() or b"{}")
            except Exception:
                pass
            reason = payload.get("error", str(e))
            if e.code == 409:
                raise ConflictError(reason) from None
            if e.code == 410:
                raise CompactedError(reason) from None
            if e.code == 404:
                raise KeyError(reason) from None
            raise RemoteStoreError(f"{e.code}: {reason}") from None
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            # transient transport failure → retryable (HTTPError is a
            # URLError subclass, so it must be handled above first)
            raise RemoteUnavailableError(str(e)) from None

    # ------------------------------------------------------ store protocol
    def get(self, kind: str, key: str):
        try:
            res = self._request("GET", f"/apis/{kind}/{key}")
        except KeyError:
            return None, 0
        return scheme.decode(res["object"]), res["resourceVersion"]

    def list(self, kind: str):
        res = self._request("GET", f"/apis/{kind}")
        return (
            [(i["key"], scheme.decode(i["object"])) for i in res["items"]],
            res["resourceVersion"],
        )

    def create(self, kind: str, key: str, obj: Any) -> int:
        res = self._request(
            "POST", f"/apis/{kind}/{key}", scheme.encode(obj)
        )
        return res["resourceVersion"]

    def update(
        self, kind: str, key: str, obj: Any, expect_rv: int | None = None
    ) -> int:
        q = f"?resourceVersion={expect_rv}" if expect_rv is not None else ""
        res = self._request(
            "PUT", f"/apis/{kind}/{key}{q}", scheme.encode(obj)
        )
        return res["resourceVersion"]

    def delete(self, kind: str, key: str) -> int:
        res = self._request("DELETE", f"/apis/{kind}/{key}")
        return res["resourceVersion"]

    def watch(self, kind: str | None, since_rv: int) -> "RemoteWatcher":
        if kind is None:
            raise RemoteStoreError("remote watch requires a kind")
        return RemoteWatcher(self, kind, since_rv)


class RemoteWatcher:
    """Pull watcher over the REST watch endpoint (Watcher protocol)."""

    def __init__(
        self, store: RemoteStore, kind: str, since_rv: int,
        poll_timeout_s: float = 0.0,
    ) -> None:
        self._store = store
        self._kind = kind
        self._rv = since_rv
        # 0 = non-blocking poll (loop-pump shape); raise for long-polling
        self.poll_timeout_s = poll_timeout_s

    @property
    def resource_version(self) -> int:
        return self._rv

    def poll(self) -> list[WatchEvent]:
        # the long-poll must stay under the transport timeout or a quiet
        # bucket reads as a (retryable) timeout every poll
        wait = min(self.poll_timeout_s, max(self._store.timeout_s - 5.0, 0.0))
        res = self._store._request(
            "GET",
            f"/apis/{self._kind}?watch=1&resourceVersion={self._rv}"
            f"&timeoutSeconds={wait}",
        )
        self._rv = res["resourceVersion"]
        return [
            WatchEvent(
                type=e["type"], kind=self._kind, key=e["key"],
                obj=scheme.decode(e["object"]),
                resource_version=e["resourceVersion"],
            )
            for e in res["events"]
        ]
