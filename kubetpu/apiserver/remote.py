"""RemoteStore — the store protocol over the API server's REST + watch.

The client-go side of the process boundary: a RemoteStore exposes the SAME
surface the in-process MemStore does (get/list/create/update/delete/watch),
so ``Reflector``/``SchedulerInformers``/``StoreClient`` and every
controller run unchanged against a remote API server — scheduler and
control plane in separate processes, exactly the reference's deployment
shape (components talk only to the apiserver, SURVEY §1).

Watch is the pull form: ``RemoteWatcher.poll`` GETs
``?watch=1&resourceVersion=<cursor>`` with a short long-poll; HTTP 410 maps
back to ``CompactedError`` so the reflector's relist path fires.

WIRE NEGOTIATION (kubetpu.api.codec): with ``wire="binary"`` (the default)
every request carries ``Accept: application/x-kubetpu-bin; v=…;
schema=<fp>``; the server replies binary only when the fingerprint matches
its own, and the first binary-typed response CONFIRMS the dialect — only
then do request bodies switch to binary (a body is never sent in a format
the server has not proven it decodes). A 415 at any point (schema drift, a
JSON-only server) drops this client to JSON permanently and re-issues the
request once — mixed-version client/server pairs keep working in both
directions. Responses always decode by their Content-Type, so the two
sides never have to agree in advance.
"""

from __future__ import annotations

import http.client
from typing import Any

from ..api import codec
from ..store.memstore import CompactedError, ConflictError, WatchEvent

BULK_SUFFIX = ":bulk"


class RemoteStoreError(Exception):
    pass


class RemoteUnavailableError(ConnectionError):
    """Transient transport failure (connection refused/reset, timeout):
    derives from ConnectionError so pump loops can catch-and-retry it the
    way client-go's ListAndWatch retries — one apiserver restart must not
    kill a component process."""


class RemoteStore:
    #: watch-path reconnect policy (see ``_watch_request``): capped
    #: jittered exponential backoff with a retry budget — a restarting
    #: apiserver is a BOUNDED stall for the informer pump, not informer
    #: death, and not an unthrottled hammer on the returning server
    WATCH_RETRY_BUDGET = 6
    BACKOFF_BASE_S = 0.05
    BACKOFF_CAP_S = 2.0
    BACKOFF_JITTER = 0.25       # +/- fraction of the delay
    #: default LIST page size: every relist is a limit/continue walk of
    #: N bounded RPCs instead of one unbounded reply — at 50k nodes the
    #: unpaged body is tens of MB in one read, the paged walk is ~100
    #: requests that each fit in a socket buffer. 0 disables paging.
    LIST_PAGE_LIMIT = 500
    #: per-PAGE retry budget (the watch path's policy applied to each
    #: page GET): a page is an idempotent snapshot-pinned read, so the
    #: capped-jitter retry that hardens watch polls is safe here too
    LIST_RETRY_BUDGET = 6

    def __init__(self, base_url: str, timeout_s: float = 30.0,
                 wire: str = "binary", traceparent: bool = False,
                 tracer=None) -> None:
        """``traceparent=True`` stamps a W3C-style trace context on every
        RPC — the ``traceparent`` header on the JSON wire, the ``tp``
        media-type parameter on the binary envelope (both through the
        codec seam, so a 415/JSON fallback carries the SAME value in the
        other slot) — and, with a ``tracer`` bound, records one client
        span per request so the apiserver's server span joins it. False
        (the default, ``--telemetry off``) is byte-identical to the
        pre-telemetry wire: no header, no parameter, no span."""
        import threading

        if wire not in ("binary", "json"):
            raise ValueError(f"wire must be binary|json, got {wire!r}")
        self.base = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self._traceparent = traceparent
        self._tracer = tracer
        # persistent per-THREAD connections (client-go's transport reuse):
        # a fresh TCP handshake per request would dominate the bind path
        self._local = threading.local()
        # negotiation state: None = undetermined (Accept advertises binary,
        # bodies still ride JSON), True = server confirmed our dialect
        # (bodies go binary), False = JSON only (wire="json", or a 415
        # dropped us there permanently). Plain attribute: worst case two
        # threads re-confirm/re-fall-back — both idempotent.
        self._wire_ok: "bool | None" = None if wire == "binary" else False
        # replicated read plane: when ``base_url`` is a FOLLOWER apiserver
        # its 307 names the leader — writes retarget there (and stay
        # there), reads/watches keep riding the follower. Cleared when the
        # leader stops answering (failover: the next 307 re-learns it).
        self._write_base: "str | None" = None
        # apiserver_client_reconnects_total{reason}: every watch-path
        # retry taken after a transport failure, by failure class, plus
        # every list-page retry under reason="list" — the
        # restart-visibility counter (guarded: watcher threads + a
        # diagnostics scrape share it)
        self._reconnect_lock = threading.Lock()
        self.reconnect_counts: dict[str, int] = {}
        # paged-relist evidence for the bench ladder: cumulative totals
        # plus the last walk's shape (pages, wire bytes, largest page) —
        # ListScaling's pages/relist and bytes/relist read from here
        self.relist_stats: dict[str, int] = {
            "relists": 0, "pages": 0, "bytes": 0, "max_page_bytes": 0,
        }
        self.last_relist: "dict[str, int] | None" = None

    # ------------------------------------------------- reconnect policy
    @staticmethod
    def _failure_reason(e: Exception) -> str:
        """Coarse failure class for the reconnect counter's label."""
        msg = str(e).lower()
        if "refused" in msg:
            return "refused"
        if "reset" in msg or "disconnected" in msg or "aborted" in msg:
            return "reset"
        if "timed out" in msg or "timeout" in msg:
            return "timeout"
        return "other"

    def _count_reconnect(self, reason: str) -> None:
        with self._reconnect_lock:
            self.reconnect_counts[reason] = (
                self.reconnect_counts.get(reason, 0) + 1
            )

    def reconnect_metrics_text(self) -> str:
        """Prometheus text for the reconnect counter — mountable as a
        diagnostics metrics source next to the scheduler set."""
        with self._reconnect_lock:
            counts = dict(self.reconnect_counts)
        lines = [
            "# HELP apiserver_client_reconnects_total Watch/long-poll "
            "retries taken after a transport failure, by failure class "
            "(list-page retries ride reason=\"list\").\n"
            "# TYPE apiserver_client_reconnects_total counter\n"
        ]
        for reason in sorted(counts):
            lines.append(
                "apiserver_client_reconnects_total"
                f"{{reason=\"{reason}\"}} {counts[reason]}\n"
            )
        return "".join(lines)

    def _retried_get(self, path: str, budget: int, reason_for):
        """One idempotent GET hardened for apiserver restarts: a
        transient transport failure (past ``_request``'s single provably-
        safe retry) backs off — capped, jittered, exponential — and
        retries within ``budget``, counting each retry under
        ``reason_for(exc)``. Safe only for reads whose effect does not
        move on failure (watch polls: the cursor only advances on a
        delivered reply; list pages: snapshot-pinned by the continue
        token). A budget exhausted raises the last
        RemoteUnavailableError — the caller's catch-and-retry keeps the
        component alive at its own cadence."""
        import random
        import time

        for attempt in range(budget + 1):
            if attempt:
                delay = min(
                    self.BACKOFF_BASE_S * (2 ** (attempt - 1)),
                    self.BACKOFF_CAP_S,
                )
                delay *= 1.0 + random.uniform(
                    -self.BACKOFF_JITTER, self.BACKOFF_JITTER
                )
                time.sleep(delay)
            try:
                return self._request("GET", path)
            except RemoteUnavailableError as e:
                if attempt >= budget:
                    raise       # budget spent: no retry follows, no count
                self._count_reconnect(reason_for(e))

    def _watch_request(self, path: str):
        """Watch/long-poll GET with the reconnect policy, counted by
        failure class (``_failure_reason``)."""
        return self._retried_get(
            path, self.WATCH_RETRY_BUDGET, self._failure_reason
        )

    def _list_page_request(self, path: str):
        """One LIST page GET with the same capped-jitter policy the
        watch path rides, counted under reason="list" — a 50k relist is
        N bounded, individually-retried RPCs, not one unbounded GET
        whose mid-transfer failure restarts the whole transfer."""
        return self._retried_get(
            path, self.LIST_RETRY_BUDGET, lambda _e: "list"
        )

    @property
    def wire_codec(self) -> str:
        """The codec request BODIES currently ride ("binary" only after
        the server confirmed the dialect) — the bench's wire_codec tag."""
        return codec.BINARY if self._wire_ok else codec.JSON

    # ------------------------------------------------------------ plumbing
    def _connection(self, base: "str | None" = None):
        """→ (conn, reused): ``reused`` marks a kept-alive socket — the
        idle-close race (server dropped it between our requests) is the one
        failure where resending is provably safe for any verb. One
        persistent connection per (thread, base): the write-redirect path
        talks to the leader without tearing down the follower's socket."""
        import socket
        from urllib.parse import urlsplit

        target = base or self.base
        conns = getattr(self._local, "conns", None)
        if conns is None:
            conns = self._local.conns = {}
        conn = conns.get(target)
        if conn is not None:
            return conn, True
        u = urlsplit(target)
        conn = http.client.HTTPConnection(
            u.hostname, u.port, timeout=self.timeout_s
        )
        conn.connect()
        # request bodies are small: without TCP_NODELAY, Nagle +
        # delayed-ACK stalls every keep-alive request ~40 ms
        conn.sock.setsockopt(
            socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
        )
        conns[target] = conn
        return conn, False

    def _drop_connection(self, base: "str | None" = None) -> None:
        target = base or self.base
        conns = getattr(self._local, "conns", None)
        conn = conns.pop(target, None) if conns else None
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def _request(self, method: str, path: str, body: Any = None):
        """One request through the wire seam. ``body`` is the reply-shaped
        TREE (may contain live registered dataclasses) — the negotiated
        codec encodes it here, so no caller pre-serializes. A 415 response
        means the server cannot decode our binary dialect: fall back to
        JSON permanently and re-issue once (the mixed-version path)."""
        # ONE trace context per logical request: the 415/JSON re-issue
        # below carries the SAME value back in the header envelope, so
        # the two attempts correlate as one trace
        ctx = self._trace_context()
        # writes ride the learned leader base (replicated read plane);
        # reads/watches always ride self.base — that IS the offload
        base = self._write_base if method != "GET" else None
        for _redirect in range(3):
            try:
                for _wire_attempt in range(2):
                    status, raw, resp_ct = self._request_transport(
                        method, path, body, ctx, base=base
                    )
                    if status == 415 and self._wire_ok is not False:
                        self._wire_ok = False
                        continue
                    break
            except RemoteUnavailableError:
                if base is not None:
                    # the learned leader stopped answering (failover):
                    # forget it — the next 307 from our replica names the
                    # new one
                    self._write_base = None
                raise
            if status != 307:
                break
            # follower write redirect: the reply body names the leader
            payload = {}
            try:
                payload = codec.loads(
                    raw or b"{}", codec.codec_for_content_type(resp_ct)
                )
            except Exception:  # noqa: BLE001 — fall through to the error below
                pass
            leader = (payload.get("leader") or "").rstrip("/")
            if not leader or leader == (base or self.base):
                raise RemoteStoreError(
                    "follower apiserver redirected a write but named no "
                    "usable leader"
                )
            self._write_base = base = leader
        if status < 400:
            try:
                return codec.loads(
                    raw or b"{}", codec.codec_for_content_type(resp_ct)
                )
            except codec.UnsupportedWireError as e:
                raise RemoteStoreError(f"undecodable response: {e}") \
                    from None
        payload = {}
        try:
            payload = codec.loads(
                raw or b"{}", codec.codec_for_content_type(resp_ct)
            )
        except Exception:
            pass
        reason = payload.get("error", f"HTTP {status}")
        if status == 409:
            raise ConflictError(reason)
        if status == 410:
            raise CompactedError(reason)
        if status == 404:
            raise KeyError(reason)
        if status in (400, 422):
            # 400: malformed request (bad selector); 422: strategy
            # validation rejected the object (admission.py)
            raise ValueError(reason)
        if status == 403:
            # validating admission hook vetoed the write
            raise PermissionError(reason)
        raise RemoteStoreError(f"{status}: {reason}")

    def set_tracer(self, tracer) -> None:
        """Bind the span recorder client rpc spans land in (the owning
        component's Tracer) — split from __init__ because the scheduler
        that owns the tracer is constructed around this store."""
        self._tracer = tracer

    def _trace_context(self):
        """A fresh per-request trace context when propagation is on
        (telemetry); None otherwise — and None means the request's bytes
        are identical to a pre-telemetry client's."""
        if not self._traceparent:
            return None
        from ..telemetry.context import TraceContext, new_span_id, new_trace_id

        return TraceContext(new_trace_id(), new_span_id())

    def _request_headers(self, wire_out: str, ctx=None) -> dict:
        tp = None
        if ctx is not None:
            from ..telemetry.context import format_traceparent

            tp = format_traceparent(ctx)
        if wire_out == codec.BINARY:
            # binary envelope: the traceparent rides the media type next
            # to the schema fingerprint (codec.TRACEPARENT_PARAM)
            headers = {
                "Content-Type": codec.content_type_for(wire_out, tp)
            }
        else:
            headers = {"Content-Type": codec.content_type_for(wire_out)}
            if tp:
                headers[codec.TRACEPARENT_HEADER] = tp
        if self._wire_ok is not False:
            # advertise our binary dialect (media type + schema
            # fingerprint); a server that matches replies binary and
            # thereby confirms it
            headers["Accept"] = codec.binary_content_type()
        return headers

    def _note_response_ct(self, resp_ct: "str | None") -> None:
        """First binary-typed response confirms the dialect — request
        bodies switch to binary from here on."""
        if (
            self._wire_ok is None and resp_ct
            and codec.CT_BINARY in resp_ct
        ):
            self._wire_ok = True

    def _request_transport(self, method: str, path: str, body: Any,
                           ctx=None, base: "str | None" = None):
        """The transport half with ONE safe retry. Blindly resending a
        non-idempotent verb after a transport error could double-apply it
        (a create whose response was lost resends → 409 for a create that
        SUCCEEDED), so the retry is limited to failures that prove the
        server never processed the request: a send-phase error, or the
        keep-alive idle-close race (RemoteDisconnected on a REUSED socket —
        the server dropped the idle connection before reading). GETs retry
        on any transport error; everything else surfaces as
        RemoteUnavailableError for the caller to decide. Returns
        (status, raw body, response content type)."""
        import time as _time

        wire_out = codec.BINARY if self._wire_ok else codec.JSON
        data = codec.dumps(body, wire_out) if body is not None else None
        # ``ctx`` is the caller's per-LOGICAL-request trace context: the
        # provably-safe retry below and _request's 415/JSON re-issue both
        # re-send with the same trace + span ids
        headers = self._request_headers(wire_out, ctx)
        t_span = _time.perf_counter() if ctx is not None else 0.0
        last: Exception | None = None
        for attempt in range(2):
            try:
                conn, reused = self._connection(base)
                conn.request(method, path, body=data, headers=headers)
            except (ConnectionError, TimeoutError, OSError,
                    http.client.HTTPException) as e:
                # connect or send never completed: the server never saw
                # the request, safe to retry any verb once
                self._drop_connection(base)
                last = e
                continue
            try:
                resp = conn.getresponse()
                status, raw = resp.status, resp.read()
                # per-THREAD last-response size: the paged list walk
                # reads it back per page for the bytes/relist evidence
                self._local.last_raw_len = len(raw)
                resp_ct = resp.getheader("Content-Type")
                self._note_response_ct(resp_ct)
                if ctx is not None and self._tracer is not None:
                    # the client half of the cross-process join: the
                    # server span opened for this request carries the
                    # same trace id + this span id as its parent
                    self._tracer.record(
                        f"rpc.{method}", start=t_span,
                        end=_time.perf_counter(),
                        path=path.partition("?")[0], status=status,
                        trace_id=ctx.trace_id, span_id=ctx.span_id,
                    )
                return status, raw, resp_ct
            except (ConnectionError, TimeoutError, OSError,
                    http.client.HTTPException) as e:
                self._drop_connection(base)
                last = e
                idle_close = reused and isinstance(
                    e, (http.client.RemoteDisconnected, ConnectionResetError)
                )
                if attempt == 0 and (method == "GET" or idle_close):
                    continue
                raise RemoteUnavailableError(str(e)) from None
        raise RemoteUnavailableError(str(last)) from None

    # ------------------------------------------------------ store protocol
    def get(self, kind: str, key: str):
        try:
            res = self._request("GET", f"/apis/{kind}/{key}")
        except KeyError:
            return None, 0
        return codec.as_object(res["object"]), res["resourceVersion"]

    def list(
        self, kind: str,
        label_selector: str = "", field_selector: str = "",
        limit: "int | None" = None,
    ):
        """Full LIST as a limit/continue PAGED WALK (``limit=None`` →
        ``LIST_PAGE_LIMIT``; 0 forces the legacy single unpaged GET).
        Every page is snapshot-pinned by the server's continue token and
        individually retried within ``LIST_RETRY_BUDGET``
        (``_list_page_request``); the returned resourceVersion is the
        walk's pinned snapshot rv, so a watch opened from it replays
        exactly the mid-walk delta. A mid-walk 410 (token outlived the
        event-log compaction window) restarts ONE fresh walk; the walk's
        shape lands in ``relist_stats``/``last_relist``."""
        page_limit = self.LIST_PAGE_LIMIT if limit is None else limit
        sel = _sel_qs("&", label_selector, field_selector)
        restarts = 0
        while True:
            try:
                return self._list_walk(kind, sel, page_limit)
            except CompactedError:
                if page_limit <= 0 or restarts >= 1:
                    raise
                restarts += 1

    def _list_walk(self, kind: str, sel: str, page_limit: int):
        """One attempted walk (or the one unpaged GET when
        ``page_limit`` ≤ 0). Raises CompactedError if a continue token
        expires mid-walk — ``list`` restarts fresh."""
        items: list = []
        rv = 0
        cont = ""
        pages = total_bytes = max_page = 0
        while True:
            if page_limit > 0:
                path = (
                    f"/apis/{kind}?limit={page_limit}"
                    + (f"&continue={cont}" if cont else "") + sel
                )
            else:
                path = f"/apis/{kind}" + (("?" + sel[1:]) if sel else "")
            res = self._list_page_request(path)
            page_bytes = getattr(self._local, "last_raw_len", 0)
            pages += 1
            total_bytes += page_bytes
            max_page = max(max_page, page_bytes)
            items.extend(
                (i["key"], codec.as_object(i["object"]))
                for i in res["items"]
            )
            rv = res["resourceVersion"]
            cont = res.get("continue", "")
            if not cont:
                break
        with self._reconnect_lock:
            self.relist_stats["relists"] += 1
            self.relist_stats["pages"] += pages
            self.relist_stats["bytes"] += total_bytes
            self.relist_stats["max_page_bytes"] = max(
                self.relist_stats["max_page_bytes"], max_page
            )
            self.last_relist = {
                "pages": pages, "bytes": total_bytes,
                "max_page_bytes": max_page,
            }
        return items, rv

    def create(self, kind: str, key: str, obj: Any) -> int:
        res = self._request("POST", f"/apis/{kind}/{key}", obj)
        return res["resourceVersion"]

    def update(
        self, kind: str, key: str, obj: Any, expect_rv: int | None = None
    ) -> int:
        q = f"?resourceVersion={expect_rv}" if expect_rv is not None else ""
        res = self._request("PUT", f"/apis/{kind}/{key}{q}", obj)
        return res["resourceVersion"]

    def delete(self, kind: str, key: str) -> int:
        res = self._request("DELETE", f"/apis/{kind}/{key}")
        return res["resourceVersion"]

    def bulk(self, kind: str, ops: list[dict]) -> list[dict]:
        """POST /apis/<kind>:bulk — N ops, ONE round trip, positional
        per-op results (``MemStore.bulk``'s shape: {"status",
        "resourceVersion", "error"?, "object"?}, objects decoded). Per-op
        failures ride the result list — only transport / whole-request
        errors raise. The one-safe-retry discipline applies per BATCH
        (``_request``'s send-phase / idle-close rules), so a batch is
        never double-applied."""
        wire = []
        for op in ops:
            w = {"op": op["op"], "key": op["key"]}
            if "object" in op:
                w["object"] = op["object"]    # live; the codec encodes it
            if op.get("expect_rv") is not None:
                w["resourceVersion"] = op["expect_rv"]
            wire.append(w)
        res = self._request("POST", f"/apis/{kind}{BULK_SUFFIX}",
                            {"ops": wire})
        out = []
        for r in res["results"]:
            if r.get("object") is not None:
                r = dict(r, object=codec.as_object(r["object"]))
            out.append(r)
        return out

    def watch_bulk(
        self, cursors: dict[str, int], timeout_s: float = 0.0
    ) -> dict:
        """Batched watch poll: every kind's cursor drained in ONE request
        (GET /apis/?watch=1&buckets=…). Returns {kind: (events, cursor)}
        with a CompactedError VALUE for a compacted kind (the caller
        relists just that kind — the other buckets' deliveries still
        land)."""
        qs = ",".join(f"{k}:{rv}" for k, rv in cursors.items())
        res = self._watch_request(
            f"/apis/?watch=1&buckets={qs}&timeoutSeconds={timeout_s}",
        )
        out: dict = {}
        for kind, bucket in res["buckets"].items():
            if bucket.get("code") == 410:
                out[kind] = CompactedError(bucket.get("error", "compacted"))
                continue
            out[kind] = (
                [
                    WatchEvent(
                        type=e["type"], kind=kind, key=e["key"],
                        obj=codec.as_object(e["object"]),
                        resource_version=e["resourceVersion"],
                    )
                    for e in bucket["events"]
                ],
                bucket["resourceVersion"],
            )
        return out

    def watch(
        self, kind: str | None, since_rv: int,
        label_selector: str = "", field_selector: str = "",
        stream: bool = False,
    ):
        if kind is None:
            raise RemoteStoreError("remote watch requires a kind")
        if stream:
            return RemoteStreamWatcher(
                self, kind, since_rv, label_selector, field_selector
            )
        return RemoteWatcher(
            self, kind, since_rv,
            label_selector=label_selector, field_selector=field_selector,
        )


def _sel_qs(prefix: str, label_selector: str, field_selector: str) -> str:
    from urllib.parse import quote

    parts = []
    if label_selector:
        parts.append(f"labelSelector={quote(label_selector)}")
    if field_selector:
        parts.append(f"fieldSelector={quote(field_selector)}")
    if not parts:
        return ""
    return prefix + "&".join(parts)


class RemoteWatcher:
    """Pull watcher over the REST watch endpoint (Watcher protocol)."""

    def __init__(
        self, store: RemoteStore, kind: str, since_rv: int,
        poll_timeout_s: float = 0.0,
        label_selector: str = "", field_selector: str = "",
    ) -> None:
        self._store = store
        self._kind = kind
        self._rv = since_rv
        self._sel = _sel_qs("&", label_selector, field_selector)
        # 0 = non-blocking poll (loop-pump shape); raise for long-polling
        self.poll_timeout_s = poll_timeout_s

    @property
    def resource_version(self) -> int:
        return self._rv

    @property
    def bulk_pollable(self) -> bool:
        """Eligible for the informer bundle's batched multi-kind poll —
        only an unscoped watcher (the batched endpoint carries no
        selector state)."""
        return not self._sel

    def advance(self, cursor: int) -> None:
        """Move the cursor after a batched poll delivered this kind's
        events out-of-band."""
        self._rv = cursor

    def poll(self) -> list[WatchEvent]:
        # the long-poll must stay under the transport timeout or a quiet
        # bucket reads as a (retryable) timeout every poll; the backoff-
        # hardened watch request rides out an apiserver restart
        wait = min(self.poll_timeout_s, max(self._store.timeout_s - 5.0, 0.0))
        res = self._store._watch_request(
            f"/apis/{self._kind}?watch=1&resourceVersion={self._rv}"
            f"&timeoutSeconds={wait}{self._sel}",
        )
        self._rv = res["resourceVersion"]
        return [
            WatchEvent(
                type=e["type"], kind=self._kind, key=e["key"],
                obj=codec.as_object(e["object"]),
                resource_version=e["resourceVersion"],
            )
            for e in res["events"]
        ]


class RemoteStreamWatcher:
    """STREAMING watcher: one chunked ndjson connection held open by the
    server (?watch=1&stream=1), a blocking reader thread decoding events as
    lines arrive (a non-blocking line read over a buffered socket could
    tear a line) — the reference's watch-stream shape. ``poll()`` stays
    non-blocking (drains the decoded queue), so the Reflector pump loop
    runs unchanged; a dropped/expired connection re-opens transparently
    from the cursor on the next poll; an in-stream 410 raises
    CompactedError (relist)."""

    def __init__(
        self, store: RemoteStore, kind: str, since_rv: int,
        label_selector: str = "", field_selector: str = "",
        stream_timeout_s: float = 120.0,
    ) -> None:
        import collections
        import threading

        self._store = store
        self._kind = kind
        self._rv = since_rv
        self._sel = _sel_qs("&", label_selector, field_selector)
        self._stream_timeout_s = stream_timeout_s
        self._lock = threading.Lock()
        self._queue: collections.deque = collections.deque()
        self._thread: threading.Thread | None = None
        self._sock = None
        self._closed = False
        self.reconnects = 0

    @property
    def resource_version(self) -> int:
        return self._rv

    def _reader(self, start_rv: int) -> None:
        """One connection's lifetime: connect, decode frames, enqueue.
        The stream's framing follows the response Content-Type — ndjson
        lines, or u32-length-prefixed binary frames when the server
        negotiated our binary dialect (the Accept header below). Ends on
        EOF/error; poll() restarts it from the current cursor."""
        from urllib.parse import urlsplit

        conn = resp = None
        try:
            u = urlsplit(self._store.base)
            conn = http.client.HTTPConnection(
                u.hostname, u.port,
                timeout=self._stream_timeout_s + self._store.timeout_s,
            )
            headers = {}
            if self._store._wire_ok is not False:
                headers["Accept"] = codec.binary_stream_content_type()
            ctx = self._store._trace_context()
            if ctx is not None:
                from ..telemetry.context import format_traceparent

                # a stream GET carries no body, so the header is the
                # envelope on both wires
                headers[codec.TRACEPARENT_HEADER] = format_traceparent(ctx)
            conn.request(
                "GET",
                f"/apis/{self._kind}?watch=1&stream=1"
                f"&resourceVersion={start_rv}"
                f"&timeoutSeconds={self._stream_timeout_s}{self._sel}",
                headers=headers,
            )
            resp = conn.getresponse()
            self._sock = conn.sock   # close() shutdowns this to wake us
            if resp.status != 200:
                body = resp.read()
                self._queue.append((
                    "error",
                    CompactedError(body.decode(errors="replace"))
                    if resp.status == 410
                    else RemoteStoreError(f"{resp.status}: {body[:200]!r}"),
                ))
                return
            ct = resp.getheader("Content-Type") or ""
            if codec.CT_BINARY in ct:
                self._read_binary_frames(resp)
            else:
                self._read_ndjson(resp)
        except (ConnectionError, TimeoutError, OSError,
                http.client.HTTPException,
                AttributeError, ValueError):
            # stream died (or close() tore the socket out from under a
            # buffered read): next poll reconnects from the cursor
            pass
        finally:
            self._sock = None
            sock = conn.sock if conn is not None else None
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass

    def _enqueue(self, msg: dict) -> bool:
        """One decoded frame → the queue; False ends the stream (410)."""
        if msg.get("code") == 410:
            self._queue.append(
                ("error", CompactedError(msg.get("error", "compacted")))
            )
            return False
        self._queue.append(("event", msg))
        return True

    def _read_ndjson(self, resp) -> None:
        for raw in resp:
            line = raw.strip()
            if not line:
                continue
            try:
                msg = codec.loads(line, codec.JSON)
            except codec.UnsupportedWireError:
                continue
            if not self._enqueue(msg):
                return

    def _read_binary_frames(self, resp) -> None:
        """u32-LE length prefix + one self-contained binary value per
        frame (codec.stream_frame's negotiated form)."""
        def read_exact(n: int) -> bytes:
            chunks = []
            while n:
                got = resp.read(n)
                if not got:
                    return b""
                chunks.append(got)
                n -= len(got)
            return b"".join(chunks)

        while True:
            head = read_exact(4)
            if len(head) < 4:
                return                      # EOF between frames
            body = read_exact(int.from_bytes(head, "little"))
            if not body:
                return
            try:
                msg = codec.loads(body, codec.BINARY)
            except codec.UnsupportedWireError:
                return                      # torn frame: reconnect
            if not self._enqueue(msg):
                return

    def poll(self) -> list[WatchEvent]:
        import threading

        out: list[WatchEvent] = []
        while self._queue:
            tag, payload = self._queue.popleft()
            if tag == "error":
                raise payload
            self._rv = payload["resourceVersion"]
            out.append(WatchEvent(
                type=payload["type"], kind=self._kind, key=payload["key"],
                obj=codec.as_object(payload["object"]),
                resource_version=payload["resourceVersion"],
            ))
        if not self._closed and (
            self._thread is None or not self._thread.is_alive()
        ):
            with self._lock:
                if self._thread is None or not self._thread.is_alive():
                    self.reconnects += 1
                    self._thread = threading.Thread(
                        target=self._reader, args=(self._rv,), daemon=True,
                    )
                    self._thread.start()
        return out

    def close(self) -> None:
        """Tear the stream down NOW: a plain conn.close() would try to
        drain the unfinished chunked body (blocking up to the stream
        deadline) and would not wake the reader's blocked recv — a socket
        shutdown does both."""
        import socket as _socket

        self._closed = True
        sock = self._sock
        if sock is not None:
            try:
                sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
