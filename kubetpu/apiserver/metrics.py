"""API server request metrics — the apiserver/pkg/endpoints/metrics slice.

Reference names and shapes (metrics.go):

- ``apiserver_request_duration_seconds{verb, resource, code}`` — the
  reference's requestLatencies bucket list, 5 ms … 60 s
- ``apiserver_request_total{verb, resource, code}``
- ``apiserver_current_inflight_requests{request_kind}`` — readOnly vs
  mutating, the max-in-flight filter's gauge; long-running requests
  (watch streams) are EXCLUDED (the reference's longrunning predicate)
  and counted on
- ``apiserver_longrunning_requests{verb, resource}`` instead
"""

from __future__ import annotations

import re
import time
from contextlib import contextmanager
from typing import Callable

from ..metrics.registry import Registry

# apiserver/pkg/endpoints/metrics/metrics.go requestLatencies buckets
REQUEST_DURATION_BUCKETS = [
    0.005, 0.025, 0.05, 0.1, 0.2, 0.4, 0.6, 0.8, 1.0, 1.25, 1.5,
    2, 3, 4, 5, 6, 8, 10, 15, 20, 30, 45, 60,
]

READ_VERBS = frozenset({"GET", "LIST", "WATCH"})

#: distinct resource label values admitted before folding into "other" —
#: the resource segment is CLIENT-supplied path text, and every unseen
#: label tuple mints new metric children, so an unbounded scanner would
#: otherwise grow the registry without limit (the reference only records
#: recognized resources)
MAX_RESOURCE_LABELS = 64

#: resource path segments are CLIENT text; only lowercase-DNS-label names
#: (the shape of every real resource: "pods", "poddisruptionbudgets") may
#: ever become a label value — anything else folds to "other" before it
#: can reach the exposition
_RESOURCE_RE = re.compile(r"[a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?$")

#: verbs whose 2xx proves the resource kind really exists: a write decoded
#: through the scheme, or a keyed GET that found an object. LIST/WATCH of
#: an unknown kind "succeed" with an empty result, so their 200s admit
#: nothing — the list handler admits explicitly once it returns items.
_PROVING_VERBS = frozenset({"GET", "CREATE", "UPDATE", "PATCH", "DELETE"})


class APIServerMetrics:
    """Owns a Registry with the apiserver request metric set; the handler
    observes through ``track``."""

    def __init__(self, registry: Registry | None = None) -> None:
        import threading

        r = registry if registry is not None else Registry()
        self.registry = r
        self._resources: set[str] = set()
        self._resources_lock = threading.Lock()
        self.request_duration = r.histogram(
            "apiserver_request_duration_seconds",
            "Response latency distribution in seconds for each verb and "
            "resource.",
            labels=("verb", "resource", "code"),
            buckets=REQUEST_DURATION_BUCKETS,
        )
        self.request_total = r.counter(
            "apiserver_request_total",
            "Counter of apiserver requests broken out for each verb, "
            "resource and HTTP response code.",
            labels=("verb", "resource", "code"),
        )
        self.inflight = r.gauge(
            "apiserver_current_inflight_requests",
            "Maximal number of currently used inflight request limit of "
            "this apiserver per request kind in last second.",
            labels=("request_kind",),
        )
        self.longrunning = r.gauge(
            "apiserver_longrunning_requests",
            "Gauge of all active long-running apiserver requests "
            "(watch streams).",
            labels=("verb", "resource"),
        )
        # the wire-protocol evidence counter: payload bytes by codec and
        # direction (request bodies in, reply/stream bodies out) — the
        # bench ladder's wire_bytes_per_pod numerator and the ≥60%
        # byte-reduction acceptance read from here
        self.wire_bytes = r.counter(
            "apiserver_wire_bytes_total",
            "Request and response wire payload bytes by codec and "
            "direction.",
            labels=("codec", "direction"),
            declared={
                "codec": ("json", "binary"),
                "direction": ("in", "out"),
            },
        )

        # the read plane's pagination evidence: one increment per LIST
        # reply, split by whether the limit/continue walk served it
        # (ListScaling's pages/relist reads from here)
        self.list_pages = r.counter(
            "apiserver_list_pages_total",
            "LIST replies served, by pagination mode (paged = a "
            "limit/continue page, full = the unpaged monolithic reply).",
            labels=("mode",),
            declared={"mode": ("paged", "full")},
        )
        # replication-feed egress by path — the chained-shipping
        # acceptance (leader egress ~= one follower's worth) reads the
        # leader's log-path delta
        self.replication_bytes = r.counter(
            "apiserver_replication_bytes_total",
            "Replication feed payload bytes served, by path.",
            labels=("path",),
            declared={"path": ("log", "snapshot")},
        )
        # lag (records) the last rv=0 bounded-staleness list trailed the
        # leader by; None until one is served. Exposed as
        # store_list_lag_records by the follower's metrics source only —
        # unreplicated/leader servers omit the series so the sentinel's
        # list-lag rule stays dormant there
        self.list_lag_last: int | None = None

    def count_wire(self, codec: str, direction: str, n: int) -> None:
        """Record ``n`` payload bytes moving through the wire seam."""
        if n:
            self.wire_bytes.labels(codec, direction).inc(n)

    def count_replication(self, path: str, n: int) -> None:
        """Record ``n`` replication-feed payload bytes served."""
        if n:
            self.replication_bytes.labels(path).inc(n)

    def replication_bytes_total(self, path: str | None = None) -> int:
        """Lifetime replication-feed egress bytes, optionally by path —
        the chained-shipping bench's leader-egress probe."""
        total = 0
        for key, child in self.replication_bytes._children_snapshot():
            if path is not None and key[0] != path:
                continue
            total += int(child.value)
        return total

    def wire_bytes_total(self, codec: str | None = None,
                         direction: str | None = None) -> int:
        """Lifetime wire payload bytes, optionally filtered by codec
        and/or direction — the perf harness's wire-traffic numerator."""
        total = 0
        for key, child in self.wire_bytes._children_snapshot():
            c, d = key
            if codec is not None and c != codec:
                continue
            if direction is not None and d != direction:
                continue
            total += int(child.value)
        return total

    def admit_resource(self, resource: str) -> str:
        """Admit ``resource`` as a label value once the caller has PROOF
        the kind exists (a keyed read/write succeeded, or a list returned
        items). Malformed names and overflow beyond MAX_RESOURCE_LABELS
        fold to "other"."""
        if not _RESOURCE_RE.fullmatch(resource):
            return "other"
        with self._resources_lock:
            if resource in self._resources:
                return resource
            if len(self._resources) < MAX_RESOURCE_LABELS:
                self._resources.add(resource)
                return resource
        return "other"

    def _resource_label(self, resource: str, succeeded: bool) -> str:
        """Admission is gated on a response that PROVES the kind exists:
        a scanner's junk paths fail (404/400) or prove nothing (empty
        LIST) and fold into "other", so they can never squat the slots
        real resources need."""
        if succeeded:
            return self.admit_resource(resource)
        if not _RESOURCE_RE.fullmatch(resource):
            return "other"
        with self._resources_lock:
            if resource in self._resources:
                return resource
        return "other"

    @contextmanager
    def track(self, verb: str, resource: str, status: Callable[[], int],
              long_running: bool = False):
        """Instrument one request: in-flight (or long-running) gauge for
        the request's lifetime, duration + total observed at completion
        with the status ``status()`` reports then."""
        kind = "readOnly" if verb in READ_VERBS else "mutating"
        gauge = (
            # gauge label resolves on entry: already-admitted resources
            # keep their name, never-seen ones ride "other" until a
            # success admits them
            self.longrunning.labels(
                verb, self._resource_label(resource, succeeded=False)
            )
            if long_running else self.inflight.labels(kind)
        )
        gauge.inc()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            gauge.dec()
            code = status()
            label = self._resource_label(
                resource,
                succeeded=(verb in _PROVING_VERBS and 200 <= code < 400),
            )
            self.request_duration.labels(verb, label, str(code)).observe(
                time.perf_counter() - t0
            )
            self.request_total.labels(verb, label, str(code)).inc()

    def total_requests(self) -> int:
        """Lifetime completed-request count across every verb/resource/code
        — the perf harness's numerator for API round trips per scheduled
        pod (watch long-polls complete per poll, so they count; a held-open
        stream counts once at close)."""
        return int(sum(
            child.value
            for _key, child in self.request_total._children_snapshot()
        ))

    def expose(self) -> str:
        return self.registry.expose()
