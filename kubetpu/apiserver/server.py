"""API server — REST + watch over the versioned store (layer 3).

Reference shape (staging/src/k8s.io/apiserver): per-resource REST verbs
installed over generic storage (`registerResourceHandlers`,
endpoints/installer.go:288; generic registry Store, registry/store.go:514)
with watch streams fanned out from the watch cache (cacher.go:263). The
envelope here:

    GET    /apis/<kind>                 list → {"items": [...], "resourceVersion": N}
    GET    /apis/<kind>?watch=1&resourceVersion=N
                                        drain events AFTER N (long-poll up to
                                        ``timeoutSeconds``); 410 Gone when N
                                        predates the event buffer (relist)
    GET    /apis/<kind>/<key…>          get → {"object": …, "resourceVersion": N}
    POST   /apis/<kind>/<key…>          create (409 on exists)
    PUT    /apis/<kind>/<key…>[?resourceVersion=N]
                                        update; CAS conflict → 409
    DELETE /apis/<kind>/<key…>          delete (404 when absent)

Objects ride the Scheme codec (kubetpu.api.scheme — the "kind"-tagged JSON
serializer), so any registered type round-trips. The watch response is the
pull form of the reference's chunked watch stream: clients poll with their
cursor, the server long-polls against the store's condition variable —
the Reflector's ListAndWatch maps onto exactly these two endpoints
(see kubetpu.apiserver.remote.RemoteStore).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from ..api import scheme
from ..store.memstore import CompactedError, ConflictError, MemStore

PREFIX = "/apis/"


class _Handler(BaseHTTPRequestHandler):
    store: MemStore   # bound by the server factory
    protocol_version = "HTTP/1.1"

    def log_message(self, *args) -> None:
        pass

    # ------------------------------------------------------------ plumbing
    def _reply(self, obj, status: int = 200) -> None:
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, status: int, reason: str) -> None:
        self._reply({"error": reason}, status=status)

    def _route(self):
        """(kind, key or None, query) — key may contain '/'."""
        parts = urlsplit(self.path)
        if not parts.path.startswith(PREFIX):
            return None, None, {}
        rest = parts.path[len(PREFIX):].strip("/")
        q = {k: v[-1] for k, v in parse_qs(parts.query).items()}
        if not rest:
            return None, None, q
        kind, _, key = rest.partition("/")
        return kind, (key or None), q

    def _read_body(self):
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length) if length else b"{}"
        return json.loads(raw or b"{}")

    # --------------------------------------------------------------- verbs
    def do_GET(self) -> None:  # noqa: N802
        kind, key, q = self._route()
        if kind is None:
            self._error(404, "unknown path")
            return
        try:
            if key is None and q.get("watch"):
                self._watch(kind, q)
            elif key is None:
                items, rv = self.store.list(kind)
                self._reply({
                    "items": [
                        {"key": k, "object": scheme.encode(o)}
                        for k, o in items
                    ],
                    "resourceVersion": rv,
                })
            else:
                obj, rv = self.store.get(kind, key)
                if obj is None:
                    self._error(404, f"{kind}/{key} not found")
                else:
                    self._reply({
                        "object": scheme.encode(obj), "resourceVersion": rv,
                    })
        except Exception as e:
            self._error(500, f"{type(e).__name__}: {e}")

    def _watch(self, kind: str, q: dict) -> None:
        rv = int(q.get("resourceVersion", 0))
        timeout = min(float(q.get("timeoutSeconds", 10)), 60.0)
        try:
            events, cursor = self.store._events_since(kind, rv)
            if not events and timeout > 0:
                self.store.wait_for(rv, timeout=timeout)
                events, cursor = self.store._events_since(kind, rv)
        except CompactedError as e:
            # the watch cache's "too old resource version" → HTTP 410
            self._error(410, str(e))
            return
        self._reply({
            "events": [
                {
                    "type": e.type, "key": e.key,
                    "object": scheme.encode(e.obj),
                    "resourceVersion": e.resource_version,
                }
                for e in events
            ],
            "resourceVersion": cursor,
        })

    def do_POST(self) -> None:  # noqa: N802
        kind, key, _ = self._route()
        if kind is None or key is None:
            self._error(404, "kind and key required")
            return
        try:
            obj = scheme.decode(self._read_body())
            rv = self.store.create(kind, key, obj)
            self._reply({"resourceVersion": rv}, status=201)
        except ConflictError as e:
            self._error(409, str(e))
        except scheme.SchemeError as e:
            self._error(400, str(e))
        except Exception as e:
            self._error(500, f"{type(e).__name__}: {e}")

    def do_PUT(self) -> None:  # noqa: N802
        kind, key, q = self._route()
        if kind is None or key is None:
            self._error(404, "kind and key required")
            return
        try:
            obj = scheme.decode(self._read_body())
            expect = (
                int(q["resourceVersion"]) if "resourceVersion" in q else None
            )
            rv = self.store.update(kind, key, obj, expect_rv=expect)
            self._reply({"resourceVersion": rv})
        except ConflictError as e:
            self._error(409, str(e))
        except scheme.SchemeError as e:
            self._error(400, str(e))
        except Exception as e:
            self._error(500, f"{type(e).__name__}: {e}")

    def do_DELETE(self) -> None:  # noqa: N802
        kind, key, _ = self._route()
        if kind is None or key is None:
            self._error(404, "kind and key required")
            return
        try:
            rv = self.store.delete(kind, key)
            self._reply({"resourceVersion": rv})
        except KeyError:
            self._error(404, f"{kind}/{key} not found")
        except Exception as e:
            self._error(500, f"{type(e).__name__}: {e}")


class APIServer:
    """In-process HTTP front for a MemStore (httptest.NewServer shape)."""

    def __init__(
        self, store: MemStore | None = None,
        host: str = "127.0.0.1", port: int = 0,
    ) -> None:
        self.store = store if store is not None else MemStore()
        handler = type("BoundHandler", (_Handler,), {"store": self.store})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "APIServer":
        self._thread.start()
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
