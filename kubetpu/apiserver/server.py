"""API server — REST + watch over the versioned store (layer 3).

Reference shape (staging/src/k8s.io/apiserver): per-resource REST verbs
installed over generic storage (`registerResourceHandlers`,
endpoints/installer.go:288; generic registry Store, registry/store.go:514)
with watch streams fanned out from the watch cache (cacher.go:263). The
envelope here:

    GET    /apis/<kind>                 list → {"items": [...], "resourceVersion": N}
    GET    /apis/<kind>?watch=1&resourceVersion=N
                                        drain events AFTER N (long-poll up to
                                        ``timeoutSeconds``); 410 Gone when N
                                        predates the event buffer (relist)
    GET    /apis/<kind>?watch=1&stream=1&resourceVersion=N
                                        STREAMING watch: chunked ndjson, one
                                        event per line, the connection held
                                        open up to ``timeoutSeconds`` —
                                        the reference's watch stream shape
                                        (cacher.go fan-out); long-poll above
                                        stays as the fallback
    both list and watch accept ``labelSelector`` / ``fieldSelector``
    (``k=v,k2!=v2``) applied SERVER-side (endpoints/installer.go:288 list
    options; spec.nodeName is how a kubelet watches only its own pods) —
    a non-matching ADDED/MODIFIED is delivered as a DELETED tombstone with
    no object body
    GET    /apis/<kind>/<key…>          get → {"object": …, "resourceVersion": N}
    POST   /apis/<kind>/<key…>          create (409 on exists)
    PUT    /apis/<kind>/<key…>[?resourceVersion=N]
                                        update; CAS conflict → 409
    DELETE /apis/<kind>/<key…>          delete (404 when absent)

Objects ride the Scheme codec (kubetpu.api.scheme — the "kind"-tagged JSON
serializer), so any registered type round-trips. The watch response is the
pull form of the reference's chunked watch stream: clients poll with their
cursor, the server long-polls against the store's condition variable —
the Reflector's ListAndWatch maps onto exactly these two endpoints
(see kubetpu.apiserver.remote.RemoteStore).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from ..api import scheme
from ..metrics.health import HealthChecks
from ..store.memstore import CompactedError, ConflictError, MemStore
from .admission import AdmissionDenied, Registry, ValidationError
from .metrics import APIServerMetrics

PREFIX = "/apis/"


class _Handler(BaseHTTPRequestHandler):
    store: MemStore     # bound by the server factory
    registry: Registry  # admission + validation chain (bound by the factory)
    metrics: APIServerMetrics   # request instrumentation (bound by factory)
    health: HealthChecks        # /healthz /readyz /livez (bound by factory)
    metrics_sources: tuple = ()  # extra Prometheus-text providers
    protocol_version = "HTTP/1.1"

    def log_message(self, *args) -> None:
        pass

    # ------------------------------------------------------------ plumbing
    def _reply(self, obj, status: int = 200) -> None:
        self._status = status
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, body: str, status: int = 200,
                    content_type: str = "text/plain; charset=utf-8") -> None:
        self._status = status
        data = body.encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _error(self, status: int, reason: str) -> None:
        self._reply({"error": reason}, status=status)

    def _route(self):
        """(kind, key or None, query) — key may contain '/'."""
        parts = urlsplit(self.path)
        if not parts.path.startswith(PREFIX):
            return None, None, {}
        rest = parts.path[len(PREFIX):].strip("/")
        q = {k: v[-1] for k, v in parse_qs(parts.query).items()}
        if not rest:
            return None, None, q
        kind, _, key = rest.partition("/")
        return kind, (key or None), q

    def _read_body(self):
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length) if length else b"{}"
        return json.loads(raw or b"{}")

    # -------------------------------------------------------- diagnostics
    def _serve_diagnostics(self) -> None:
        """GET outside /apis/: /metrics (Prometheus text 0.0.4, the server
        set plus any extra bound sources) and the component-base-style
        /healthz /readyz /livez named-check endpoints — served through the
        shared mux (kubetpu.metrics.diagmux) the scheduler listener also
        mounts."""
        from ..metrics.diagmux import diagnostics_response

        parts = urlsplit(self.path)
        try:
            res = diagnostics_response(
                parts.path, parse_qs(parts.query, keep_blank_values=True),
                metrics_sources=(self.metrics.expose, *self.metrics_sources),
                health=self.health,
            )
        except Exception as e:  # noqa: BLE001 — diagnostics must not crash
            self._error(500, f"{type(e).__name__}: {e}")
            return
        if res is None:
            self._error(404, "unknown path")
            return
        status, content_type, body = res
        self._reply_text(body, status=status, content_type=content_type)

    # --------------------------------------------------------------- verbs
    def do_GET(self) -> None:  # noqa: N802
        if not urlsplit(self.path).path.startswith(PREFIX):
            self._serve_diagnostics()
            return
        kind, key, q = self._route()
        if kind is None:
            self._error(404, "unknown path")
            return
        if key is None and q.get("watch"):
            verb = "WATCH"
        elif key is None:
            verb = "LIST"
        else:
            verb = "GET"
        with self.metrics.track(
            verb, kind, lambda: getattr(self, "_status", 0),
            # EVERY watch is long-running (the reference's longrunning
            # predicate covers long-polls too): a blocked wait_for must not
            # hold the in-flight gauge
            long_running=(verb == "WATCH"),
        ):
            self._do_get(kind, key, q)

    def _do_get(self, kind, key, q) -> None:
        try:
            if key is None and q.get("watch"):
                if q.get("stream"):
                    self._watch_stream(kind, q)
                else:
                    self._watch(kind, q)
            elif key is None:
                items, rv = self.store.list(
                    kind,
                    label_selector=q.get("labelSelector", ""),
                    field_selector=q.get("fieldSelector", ""),
                )
                if items:
                    # a non-empty list proves the kind exists; an empty
                    # 200 proves nothing (MemStore lists unknown kinds as
                    # empty), so bare LIST successes never admit labels
                    self.metrics.admit_resource(kind)
                self._reply({
                    "items": [
                        {"key": k, "object": scheme.encode(o)}
                        for k, o in items
                    ],
                    "resourceVersion": rv,
                })
            else:
                obj, rv = self.store.get(kind, key)
                if obj is None:
                    self._error(404, f"{kind}/{key} not found")
                else:
                    self._reply({
                        "object": scheme.encode(obj), "resourceVersion": rv,
                    })
        except ValueError as e:
            # malformed selector / resourceVersion: the CLIENT's error —
            # a retry-on-5xx loop must not hammer a permanently-bad request
            self._error(400, str(e))
        except Exception as e:
            self._error(500, f"{type(e).__name__}: {e}")

    @staticmethod
    def _selector_view(q: dict):
        """Per-watch SelectorView, or None without selectors. The streaming
        watch holds ONE view for the connection's lifetime (repeat foreign
        events are dropped); a long-poll request gets a fresh view each
        time (stateless protocol — degraded to one tombstone per foreign
        key per poll, still correct)."""
        from ..store.memstore import SelectorView

        ls = q.get("labelSelector", "")
        fs = q.get("fieldSelector", "")
        return SelectorView(ls, fs) if (ls or fs) else None

    @staticmethod
    def _event_json(e, scoped: bool) -> dict:
        if scoped and e.type == "DELETED":
            # selector-scoped stream: never ship a body on DELETED (the
            # informer deletes by key; a tombstoned object may not even
            # match the selector)
            return {
                "type": "DELETED", "key": e.key, "object": None,
                "resourceVersion": e.resource_version,
            }
        return {
            "type": e.type, "key": e.key,
            "object": scheme.encode(e.obj),
            "resourceVersion": e.resource_version,
        }

    def _watch(self, kind: str, q: dict) -> None:
        rv = int(q.get("resourceVersion", 0))
        timeout = min(float(q.get("timeoutSeconds", 10)), 60.0)
        view = self._selector_view(q)
        try:
            events, cursor = self.store._events_since(kind, rv)
            if not events and timeout > 0:
                self.store.wait_for(rv, timeout=timeout)
                events, cursor = self.store._events_since(kind, rv)
        except CompactedError as e:
            # the watch cache's "too old resource version" → HTTP 410
            self._error(410, str(e))
            return
        if view is not None:
            events = view.filter(events)
        self._reply({
            "events": [
                self._event_json(e, view is not None) for e in events
            ],
            "resourceVersion": cursor,
        })

    def _watch_stream(self, kind: str, q: dict) -> None:
        """Chunked ndjson stream: events written as they happen, connection
        held open up to ``timeoutSeconds`` (capped) — the watch-stream form
        of the same cursor protocol. A compaction mid-stream emits an error
        line with code 410 and ends the stream (client relists)."""
        import time as _time

        rv = int(q.get("resourceVersion", 0))
        timeout = min(float(q.get("timeoutSeconds", 30)), 300.0)
        try:
            view = self._selector_view(q)
        except ValueError as e:
            self._error(400, str(e))
            return
        deadline = _time.monotonic() + timeout
        self._status = 200
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def chunk(line: dict) -> bool:
            data = (json.dumps(line) + "\n").encode()
            try:
                self.wfile.write(f"{len(data):x}\r\n".encode())
                self.wfile.write(data + b"\r\n")
                self.wfile.flush()
                return True
            except (BrokenPipeError, ConnectionResetError, OSError):
                return False
        try:
            while True:
                try:
                    events, cursor = self.store._events_since(kind, rv)
                except CompactedError as e:
                    chunk({"error": str(e), "code": 410})
                    break
                if view is not None:
                    events = view.filter(events)
                for e in events:
                    if not chunk(self._event_json(e, view is not None)):
                        return   # client hung up: no terminator possible
                rv = cursor
                remaining = deadline - _time.monotonic()
                if remaining <= 0 or getattr(self.server, "closing", False):
                    break
                self.store.wait_for(rv, timeout=min(remaining, 1.0))
        finally:
            try:
                self.wfile.write(b"0\r\n\r\n")   # chunked terminator
                self.wfile.flush()
            except OSError:
                pass

    def do_POST(self) -> None:  # noqa: N802
        kind, key, _ = self._route()
        if kind is None or key is None:
            self._error(404, "kind and key required")
            return
        with self.metrics.track(
            "CREATE", kind, lambda: getattr(self, "_status", 0)
        ):
            try:
                obj = scheme.decode(self._read_body())
                # decode → admission (mutating) → validate → admission
                # (validating) → storage — the reference write path
                # (registry/store.go:514 Create's strategy run). The
                # admission chain's write locks span admit AND create so a
                # usage-counting validator (quota) cannot race a concurrent
                # create of the same scope.
                with self.registry.locked(kind, key, obj, verb="create"):
                    obj = self.registry.admit(kind, key, obj, verb="create")
                    rv = self.store.create(kind, key, obj)
                self._reply({"resourceVersion": rv}, status=201)
            except ConflictError as e:
                self._error(409, str(e))
            except ValidationError as e:
                self._error(422, str(e))
            except AdmissionDenied as e:
                self._error(403, str(e))
            except scheme.SchemeError as e:
                self._error(400, str(e))
            except Exception as e:
                self._error(500, f"{type(e).__name__}: {e}")

    def do_PUT(self) -> None:  # noqa: N802
        kind, key, q = self._route()
        if kind is None or key is None:
            self._error(404, "kind and key required")
            return
        with self.metrics.track(
            "UPDATE", kind, lambda: getattr(self, "_status", 0)
        ):
            try:
                obj = scheme.decode(self._read_body())
                with self.registry.locked(kind, key, obj, verb="update"):
                    old, _old_rv = self.store.get(kind, key)
                    obj = self.registry.admit(
                        kind, key, obj, old=old, verb="update"
                    )
                    expect = (
                        int(q["resourceVersion"])
                        if "resourceVersion" in q else None
                    )
                    rv = self.store.update(kind, key, obj, expect_rv=expect)
                self._reply({"resourceVersion": rv})
            except ConflictError as e:
                self._error(409, str(e))
            except ValidationError as e:
                self._error(422, str(e))
            except AdmissionDenied as e:
                self._error(403, str(e))
            except scheme.SchemeError as e:
                self._error(400, str(e))
            except Exception as e:
                self._error(500, f"{type(e).__name__}: {e}")

    def do_DELETE(self) -> None:  # noqa: N802
        kind, key, _ = self._route()
        if kind is None or key is None:
            self._error(404, "kind and key required")
            return
        with self.metrics.track(
            "DELETE", kind, lambda: getattr(self, "_status", 0)
        ):
            try:
                rv = self.store.delete(kind, key)
                self._reply({"resourceVersion": rv})
            except KeyError:
                self._error(404, f"{kind}/{key} not found")
            except Exception as e:
                self._error(500, f"{type(e).__name__}: {e}")


class APIServer:
    """In-process HTTP front for a MemStore (httptest.NewServer shape)."""

    def __init__(
        self, store: MemStore | None = None,
        host: str = "127.0.0.1", port: int = 0,
        registry: Registry | None = None,
        metrics_sources: tuple = (),
    ) -> None:
        """``metrics_sources``: extra Prometheus-text providers appended to
        GET /metrics (e.g. a co-hosted controller family's workqueue set)."""
        self.store = store if store is not None else MemStore()
        self.registry = registry if registry is not None else Registry()
        self.metrics = APIServerMetrics()
        self.health = HealthChecks()
        # the storage-backend check (the reference's etcd check): probing
        # the store's revision counter exercises its lock + native core
        def _store_check() -> None:
            rv = self.store.resource_version   # property on MemStore
            if callable(rv):                   # method on store stand-ins
                rv()

        # healthz/readyz only — the reference excludes its etcd check
        # from /livez: a storage outage must mark the server NOT-READY,
        # not not-alive, or a liveness probe restart-loops a process
        # that is still serving watches
        self.health.add_check(
            "store", _store_check, endpoints=("healthz", "readyz")
        )
        handler = type("BoundHandler", (_Handler,), {
            "store": self.store, "registry": self.registry,
            "metrics": self.metrics, "health": self.health,
            "metrics_sources": tuple(metrics_sources),
            # responses are small; Nagle + the client's delayed ACK would
            # stall every keep-alive request ~40 ms (a handler-class knob:
            # socketserver.StreamRequestHandler.disable_nagle_algorithm)
            "disable_nagle_algorithm": True,
        })

        class _Server(ThreadingHTTPServer):
            # streaming watch handlers hold connections open (bounded by
            # their own deadlines + the `closing` flag, checked every ≤1 s);
            # close() must not block on them
            daemon_threads = True
            block_on_close = False
            closing = False

        self._httpd = _Server((host, port), handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "APIServer":
        self._thread.start()
        return self

    def close(self) -> None:
        self._httpd.closing = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
