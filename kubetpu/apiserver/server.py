"""API server — REST + watch over the versioned store (layer 3).

Reference shape (staging/src/k8s.io/apiserver): per-resource REST verbs
installed over generic storage (`registerResourceHandlers`,
endpoints/installer.go:288; generic registry Store, registry/store.go:514)
with watch streams fanned out from the watch cache (cacher.go:263). The
envelope here:

    GET    /apis/<kind>                 list → {"items": [...], "resourceVersion": N}
    GET    /apis/<kind>?watch=1&resourceVersion=N
                                        drain events AFTER N (long-poll up to
                                        ``timeoutSeconds``); 410 Gone when N
                                        predates the event buffer (relist)
    GET    /apis/<kind>?watch=1&stream=1&resourceVersion=N
                                        STREAMING watch: chunked ndjson, one
                                        event per line, the connection held
                                        open up to ``timeoutSeconds`` —
                                        the reference's watch stream shape
                                        (cacher.go fan-out); long-poll above
                                        stays as the fallback
    both list and watch accept ``labelSelector`` / ``fieldSelector``
    (``k=v,k2!=v2``) applied SERVER-side (endpoints/installer.go:288 list
    options; spec.nodeName is how a kubelet watches only its own pods) —
    a non-matching ADDED/MODIFIED is delivered as a DELETED tombstone with
    no object body
    GET    /apis/?watch=1&buckets=pods:12,nodes:7[&timeoutSeconds=T]
                                        BATCHED watch poll: drain several
                                        kinds' cursors in ONE round trip;
                                        per-kind {"events", "resourceVersion"}
                                        (or {"code": 410} — only that kind
                                        relists). One request replaces the
                                        informer bundle's N per-kind polls.
    GET    /apis/<kind>/<key…>          get → {"object": …, "resourceVersion": N}
    POST   /apis/<kind>/<key…>          create (409 on exists)
    POST   /apis/<kind>:bulk            BULK verb: {"ops": [{"op": "create|
                                        update|patch|delete|get", "key": …,
                                        "object": …, "resourceVersion": N?},
                                        …]} applied under ONE store lock
                                        acquisition → {"results": [{"status",
                                        "resourceVersion", "error"?,
                                        "object"?}, …]} positional, per-op
                                        conflict/admission semantics
                                        identical to the single-op verbs
                                        (a mid-batch 409 fails only its op)
    PUT    /apis/<kind>/<key…>[?resourceVersion=N]
                                        update; CAS conflict → 409
    DELETE /apis/<kind>/<key…>          delete (404 when absent)

Watch responses are assembled from a serialize-once event cache (the
reference watch cache's CachingObject): each event's wire body is encoded
once per (kind, resourceVersion, codec) and the cached bytes are shared
across every watcher poll, batched poll, and stream frame — N watchers pay
one encode, not N. Staleness is impossible by construction: every store
write mints a fresh resourceVersion, so a mutated object can never be
served from an old entry. When the store exposes its per-event body ring
(``MemStore.events_body_since`` — backed by the native core), the unscoped
watch paths serve cached bodies STRAIGHT from the ring without ever
materializing a WatchEvent.

Every reply rides the wire-codec seam (kubetpu.api.codec — the negotiated
serializer): the reply codec is negotiated per request from the ``Accept``
header (binary only when the client's schema fingerprint matches ours),
request bodies decode by their ``Content-Type`` (an unknown/mismatched
binary dialect 415s — the client's fall-back-to-JSON signal), and NO
handler hand-rolls serialization. The watch response is the pull form of
the reference's chunked watch stream: clients poll with their cursor, the
server long-polls against the store's condition variable — the Reflector's
ListAndWatch maps onto exactly these two endpoints (see
kubetpu.apiserver.remote.RemoteStore).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from ..api import codec, scheme
from ..metrics.health import HealthChecks
from ..store.memstore import (
    CompactedError, ConflictError, FollowerWriteError, MemStore,
)
from .admission import AdmissionDenied, Registry, ValidationError
from .metrics import APIServerMetrics
from .remote import BULK_SUFFIX   # ONE wire constant for both sides

PREFIX = "/apis/"

#: the bulk paths' exception ladder: ONE copy of the per-op
#: exception→status mapping (the inverse of memstore.bulk_result_error),
#: so the fast path, the sequential path, and the single verbs cannot
#: drift. Order matters: ValidationError IS a ValueError.
_OP_ERROR_STATUS: tuple = (
    (ConflictError, 409),
    (ValidationError, 422),
    (AdmissionDenied, 403),
    (KeyError, 404),
    ((scheme.SchemeError, ValueError), 400),
)

#: the union, for except clauses
_OP_ERRORS = (
    ConflictError, ValidationError, AdmissionDenied, KeyError,
    scheme.SchemeError, ValueError,
)


def _op_error_result(e: Exception) -> dict:
    """Map one bulk-op exception to its per-op result dict."""
    for types, status in _OP_ERROR_STATUS:
        if isinstance(e, types):
            reason = (
                str(e).strip("'\"") if isinstance(e, KeyError) else str(e)
            )
            return {"status": status, "resourceVersion": 0, "error": reason}
    raise e  # unmapped: let the request-level 500 handler see it


def _stamp_pod_ingest(kind: str, obj):
    """The attribution plane's t0 (sched.flightrecorder): a freshly created
    pod gets a trace id + monotonic ingest timestamp HERE, at REST create —
    carried through the store and every watch frame so the scheduler's
    flight recorder can attribute api_ingest/e2e latency per pod. A pod
    arriving already stamped (a relayed create, a test fixture) keeps its
    original stamp — t0 means FIRST ingest."""
    if kind != "pods" or getattr(obj, "ingest_ts", 0.0):
        return obj
    import dataclasses
    import time
    import uuid

    try:
        return dataclasses.replace(
            obj,
            trace_id=uuid.uuid4().hex[:16],
            ingest_ts=time.perf_counter(),
        )
    except TypeError:       # a pod stand-in without the stamp fields
        return obj


class EventEncodeCache:
    """Serialize-once watch fan-out (the reference watch cache's
    CachingObject, cacher/caching_object.go): one wire encoding per event
    PER CODEC, keyed by (kind, resourceVersion, codec, tombstone) — unique
    per event because every store write bumps the global revision exactly
    once — and shared by every long-poll reply, batched poll bucket, and
    stream frame. The ``tombstone`` key dimension is the selector-scoped
    view: a scoped DELETED (including a selector REWRITE of an
    ADDED/MODIFIED) ships no object body, and because the tombstone's
    bytes depend only on (key, rv) — never on WHICH selector scoped it —
    one cached tombstone serves every scoped watcher (scoped fan-out used
    to bypass the cache entirely and re-serialize per watcher per event).
    Bounded LRU sized to the store's event history TIMES the key-space
    growth (2 codecs x body/tombstone = up to 4 entries per ring event —
    an 8192-entry LRU would cover as little as a quarter of the history
    under mixed-codec scoped fan-out, quietly reintroducing per-poll
    re-encodes); hit/miss counters (merged with the store body ring's,
    when one is bound) feed the codec-labeled apiserver metric set."""

    def __init__(self, maxsize: int = 4 * 8192, store=None) -> None:
        import collections
        import threading

        self._maxsize = maxsize
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[tuple, bytes]" = (
            collections.OrderedDict()
        )
        # the store whose native body ring ALSO serves cached event bodies
        # (the unscoped fast path bypasses this LRU entirely) — its
        # hit/miss counters merge into ours so "serialize-once" reads as
        # one number regardless of which cache carried the bytes
        self._store = store
        self._hits = {codec.JSON: 0, codec.BINARY: 0}
        self._misses = {codec.JSON: 0, codec.BINARY: 0}

    def event_bytes(self, e, wire: str = codec.JSON,
                    tombstone: bool = False) -> bytes:
        # the registry generation keys the entry too: binary bodies embed
        # schema-table ids, so a kind registered after an entry was cached
        # must never let that entry splice into a new-fingerprint reply
        # (old-generation entries just age out of the LRU)
        key = (e.kind, e.resource_version, wire, tombstone,
               scheme.registry_generation())
        with self._lock:
            cached = self._entries.get(key)
            if cached is not None:
                self._entries.move_to_end(key)
                self._hits[wire] += 1
                return cached
        # encode OUTSIDE the lock, last-writer-wins on insert: when a
        # write wakes N long-poll watchers at once, the worst case is a
        # handful of concurrent encodes of one small event — cheaper than
        # ever blocking a request thread on another's encode. The steady
        # win (every later poll/stream frame reuses the bytes) is carried
        # by the LRU.
        body = codec.event_wire_bytes(
            "DELETED" if tombstone else e.type,
            e.key,
            None if tombstone else e.obj,
            e.resource_version,
            wire,
        )
        with self._lock:
            self._misses[wire] += 1
            self._entries[key] = body
            while len(self._entries) > self._maxsize:
                self._entries.popitem(last=False)
        return body

    def item_bytes(self, kind: str, key: str, obj, rv: int,
                   wire: str = codec.JSON) -> bytes:
        """One LIST item's wire body through the same serialize-once LRU
        — the paged-list splice path: keyed by (kind, rv, codec) like an
        event body (every store write mints a fresh rv, so a mutated
        object can never serve from an old entry), with an "item"
        dimension keeping list bodies distinct from watch-event bodies
        at the same rv. A 50k-node relist walk re-encodes only the
        objects that changed since the last walk."""
        cache_key = (kind, rv, wire, "item", scheme.registry_generation())
        with self._lock:
            cached = self._entries.get(cache_key)
            if cached is not None:
                self._entries.move_to_end(cache_key)
                self._hits[wire] += 1
                return cached
        body = codec.list_item_wire_bytes(key, obj, wire)
        with self._lock:
            self._misses[wire] += 1
            self._entries[cache_key] = body
            while len(self._entries) > self._maxsize:
                self._entries.popitem(last=False)
        return body

    def _ring_stats(self) -> dict:
        stats = getattr(self._store, "body_cache_stats", None)
        return stats() if stats is not None else {}

    def stats_by_codec(self) -> "dict[str, tuple[int, int]]":
        """{codec: (hits, misses)} — this LRU plus the store body ring."""
        ring = self._ring_stats()
        out = {}
        with self._lock:
            for c in (codec.JSON, codec.BINARY):
                rh, rm = ring.get(c, (0, 0))
                out[c] = (self._hits[c] + rh, self._misses[c] + rm)
        return out

    @property
    def hits(self) -> int:
        return sum(h for h, _m in self.stats_by_codec().values())

    @property
    def misses(self) -> int:
        return sum(m for _h, m in self.stats_by_codec().values())


class _Handler(BaseHTTPRequestHandler):
    store: MemStore     # bound by the server factory
    registry: Registry  # admission + validation chain (bound by the factory)
    metrics: APIServerMetrics   # request instrumentation (bound by factory)
    health: HealthChecks        # /healthz /readyz /livez (bound by factory)
    event_cache: EventEncodeCache   # serialize-once fan-out (bound by factory)
    tracer = None       # server-span recorder (bound by factory)
    collector = None    # embedded telemetry collector (bound when enabled)
    sentinel = None     # embedded anomaly sentinel (bound when enabled)
    replication = None  # LeaderLease | FollowerReplicator (when replicated)
    metrics_sources: tuple = ()  # extra Prometheus-text providers
    wire_enabled: bool = True    # False = JSON-only server (--wire json):
    #                              ignores binary Accept, 415s binary bodies
    protocol_version = "HTTP/1.1"

    def log_message(self, *args) -> None:
        pass

    # ------------------------------------------------------------- tracing
    @contextmanager
    def _track_span(self, verb: str, resource: str,
                    long_running: bool = False):
        """THE request-instrumentation seam: every handler runs under it
        (graftcheck TR003 pins this). One ``metrics.track`` window plus
        one server span recorded at completion — joined to the client's
        span when the request carried a traceparent (the ``traceparent``
        header on the JSON wire, the binary envelope's ``tp`` media-type
        parameter; a malformed value is IGNORED, never a 4xx). Pod writes
        stash their attribution ids via ``_note_pod_trace`` so the span
        links the pod's cross-process timeline."""
        from ..telemetry.context import parse_traceparent

        ctx = parse_traceparent(codec.traceparent_from_headers(self.headers))
        # per-request stash (one handler instance serves one connection's
        # requests sequentially, so a plain attribute is race-free)
        self._span_pod_traces: list[str] = []
        t0 = time.perf_counter()
        try:
            with self.metrics.track(
                verb, resource, lambda: getattr(self, "_status", 0),
                long_running=long_running,
            ):
                yield
        finally:
            attrs: dict = {
                "verb": verb, "resource": resource,
                "code": getattr(self, "_status", 0),
            }
            if ctx is not None:
                # the cross-process join: same trace id as the client's
                # rpc span, the client span as this span's remote parent
                attrs["trace_id"] = ctx.trace_id
                attrs["parent_span_id"] = ctx.span_id
            if self._span_pod_traces:
                attrs["pod_traces"] = self._span_pod_traces[:64]
            self.tracer.record(
                f"apiserver.{verb}", start=t0, end=time.perf_counter(),
                **attrs,
            )

    def _note_pod_trace(self, kind: str, obj) -> None:
        """Link this request's server span to a pod's attribution id (the
        16-hex ``trace_id`` stamped at ingest) — how an ingest or
        bind-subresource span joins the pod's scheduler-side timeline."""
        if kind != "pods":
            return
        tid = getattr(obj, "trace_id", "") or ""
        if tid:
            stash = getattr(self, "_span_pod_traces", None)
            if stash is not None and len(stash) < 64:
                stash.append(tid)

    # ------------------------------------------------------------ plumbing
    def _reply_codec(self) -> str:
        """The negotiated REPLY codec for this request: binary only when
        the Accept header names our exact binary dialect (media type +
        schema fingerprint) and the server has binary enabled — anything
        else degrades to JSON, never to an undecodable reply."""
        if not self.wire_enabled:
            return codec.JSON
        return (
            codec.BINARY
            if codec.accepts_binary(self.headers.get("Accept"))
            else codec.JSON
        )

    def _body_codec(self) -> str:
        """The codec this request's BODY is encoded in (Content-Type).
        Raises UnsupportedWireError — the 415 — for a binary dialect we
        cannot decode, or any binary body when the server is JSON-only.
        The JSON-only check parses the media type (same normalization as
        codec_for_content_type) so a mixed-case binary Content-Type
        cannot slip a binary body past --wire json."""
        ct = self.headers.get("Content-Type")
        media, _params = codec.parse_content_type(ct)
        if not self.wire_enabled and media in (
            codec.CT_BINARY, codec.CT_BINARY_STREAM,
        ):
            raise codec.UnsupportedWireError(
                "binary wire disabled on this server (negotiate JSON)"
            )
        return codec.codec_for_content_type(ct)

    def _reply(self, obj, status: int = 200) -> None:
        """One reply through the wire seam — ``obj`` may contain live
        registered dataclasses; the negotiated codec encodes them in
        place (no handler pre-serializes)."""
        wire = self._reply_codec()
        self._reply_wire(codec.dumps(obj, wire), wire, status=status)

    def _reply_wire(self, body: bytes, wire: str, status: int = 200) -> None:
        """Pre-serialized reply in ``wire`` — the serialize-once watch
        paths hand cached event bytes straight to the socket."""
        self.metrics.count_wire(wire, "out", len(body))
        self._status = status
        self.send_response(status)
        self.send_header("Content-Type", codec.content_type_for(wire))
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, body: str, status: int = 200,
                    content_type: str = "text/plain; charset=utf-8") -> None:
        self._status = status
        data = body.encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _error(self, status: int, reason: str) -> None:
        self._reply({"error": reason}, status=status)

    def _route(self):
        """(kind, key or None, query) — key may contain '/'."""
        parts = urlsplit(self.path)
        if not parts.path.startswith(PREFIX):
            return None, None, {}
        rest = parts.path[len(PREFIX):].strip("/")
        q = {k: v[-1] for k, v in parse_qs(parts.query).items()}
        if not rest:
            return None, None, q
        kind, _, key = rest.partition("/")
        return kind, (key or None), q

    def _read_body(self):
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length) if length else b""
        wire = self._body_codec()   # may raise the 415
        self.metrics.count_wire(wire, "in", len(raw))
        if not raw:
            return {}
        return codec.loads(raw, wire)

    # -------------------------------------------------------- diagnostics
    def _serve_diagnostics(self) -> None:
        """GET outside /apis/: /metrics (Prometheus text 0.0.4, the server
        set plus any extra bound sources) and the component-base-style
        /healthz /readyz /livez named-check endpoints — served through the
        shared mux (kubetpu.metrics.diagmux) the scheduler listener also
        mounts."""
        from ..metrics.diagmux import diagnostics_response

        parts = urlsplit(self.path)
        try:
            res = diagnostics_response(
                parts.path, parse_qs(parts.query, keep_blank_values=True),
                metrics_sources=(self.metrics.expose, *self.metrics_sources),
                health=self.health,
                extra={
                    # the apiserver's server spans as Chrome-trace JSON —
                    # same shape as the scheduler diagnostics /trace
                    # (non-destructive; the telemetry exporter drains)
                    "/trace": lambda q: (
                        "application/json",
                        codec.dumps(self.tracer.chrome_trace()).decode(),
                    ),
                    # the embedded sentinel's alert/bundle state — same
                    # shapes as the scheduler diagnostics endpoints
                    "/debug/alerts": lambda q: (
                        "application/json",
                        codec.dumps(self._alerts_body()).decode(),
                    ),
                    "/debug/bundle": lambda q: (
                        "application/json",
                        codec.dumps(self._bundle_body(q)).decode(),
                    ),
                },
            )
        except Exception as e:  # noqa: BLE001 — diagnostics must not crash
            self._error(500, f"{type(e).__name__}: {e}")
            return
        if res is None:
            self._error(404, "unknown path")
            return
        status, content_type, body = res
        self._reply_text(body, status=status, content_type=content_type)

    def _alerts_body(self) -> dict:
        if self.sentinel is None:
            return {"enabled": False, "alerts": [], "firing": 0}
        out = self.sentinel.alerts_json()
        out["enabled"] = True
        return out

    def _bundle_body(self, query: dict) -> dict:
        if self.sentinel is None:
            return {"enabled": False, "bundles": [], "count": 0}
        out = self.sentinel.bundles_json(query)
        out["enabled"] = True
        return out

    # --------------------------------------------------------------- verbs
    def _serve_collector(self, method: str) -> bool:
        """Embedded-collector mode: /telemetry/* routed to the bound
        collector (the apiserver doubles as the telemetry sink — one less
        process for small clusters). False when the path is not ours."""
        if self.collector is None:
            return False
        parts = urlsplit(self.path)
        if not parts.path.startswith("/telemetry/"):
            return False
        from ..telemetry.collector import handle_collector_request

        body = b""
        if method == "POST":
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length) if length else b""
        try:
            res = handle_collector_request(
                self.collector, method, parts.path,
                parse_qs(parts.query, keep_blank_values=True),
                body, self.headers.get("Content-Type"),
            )
        except codec.UnsupportedWireError as e:
            self._error(415, str(e))
            return True
        except Exception as e:  # noqa: BLE001 — telemetry must not crash
            self._error(500, f"{type(e).__name__}: {e}")
            return True
        if res is None:
            self._error(404, "unknown telemetry path")
            return True
        status, content_type, data = res
        self._reply_text(
            data.decode() if isinstance(data, bytes) else data,
            status=status, content_type=content_type,
        )
        return True

    # ---------------------------------------------------------- replication
    def _serve_replication(self, method: str) -> bool:
        """Replicated read plane (kubetpu.store.replication):
        /replication/log is the leader's ship feed (WAL frames off the
        serialize-once body ring, long-polled like a watch),
        /replication/snapshot the follower bootstrap, and
        /replication/status the election/lag probe. Mounted only when a
        replication role is bound — an unreplicated server keeps PR-16
        routing exactly (the paths fall through to diagnostics' 404).
        False when the path is not ours."""
        if self.replication is None:
            return False
        parts = urlsplit(self.path)
        if not parts.path.startswith("/replication/"):
            return False
        q = {k: v[-1] for k, v in parse_qs(parts.query).items()}
        try:
            if parts.path == "/replication/status":
                self._reply(self.replication.status())
            elif parts.path == "/replication/log":
                self._serve_replication_log(q)
            elif parts.path == "/replication/snapshot":
                from ..store.wal import encode_snapshot_stream

                items, rv = self.store.dump_with_rv()
                self._reply_rep(
                    encode_snapshot_stream(items, rv, self._rep_wire(q)),
                    rv, path="snapshot",
                )
            else:
                self._error(404, "unknown replication path")
        except ValueError as e:
            self._error(400, str(e))
        except Exception as e:  # noqa: BLE001 — replication must not crash
            self._error(500, f"{type(e).__name__}: {e}")
        return True

    def _rep_wire(self, q: dict) -> str:
        """The ship body's codec: the follower asks for one (it knows its
        own build); default to the server's negotiated-wire stance."""
        wire = q.get(
            "codec", codec.BINARY if self.wire_enabled else codec.JSON
        )
        if wire not in (codec.JSON, codec.BINARY):
            raise ValueError(f"codec must be json|binary, got {wire!r}")
        if wire == codec.BINARY and not self.wire_enabled:
            raise ValueError("binary wire disabled on this server")
        return wire

    def _serve_replication_log(self, q: dict) -> None:
        from ..store.replication import build_log_body

        after = int(q.get("after", 0))
        timeout = min(float(q.get("timeoutSeconds", 0)), 60.0)
        wire = self._rep_wire(q)
        try:
            body, cursor, n = build_log_body(self.store, after, wire)
            if not n and timeout > 0:
                # the long-poll: a leader with nothing new holds the
                # follower's request on the store's condition variable —
                # shipping latency is write-wakeup latency, not a poll
                # interval
                self.store.wait_for(after, timeout=timeout)
                body, cursor, n = build_log_body(self.store, after, wire)
        except CompactedError as e:
            # the follower's cursor predates the body ring: 410 → it
            # bootstraps from /replication/snapshot (recovery's contract)
            self._error(410, str(e))
            return
        self._reply_rep(body, cursor, wire=wire)

    def _reply_rep(self, body: bytes, cursor: int,
                   wire: str = "", path: str = "log") -> None:
        """Raw replication bytes + the feed position/fencing headers."""
        from ..store import replication as rep

        # the ship plane's egress evidence: chained fan-out is judged by
        # this counter's delta on the leader (O(fan-out), not O(followers))
        self.metrics.count_replication(path, len(body))
        self._status = 200
        self.send_response(200)
        self.send_header("Content-Type", rep.CT_WAL)
        self.send_header("Content-Length", str(len(body)))
        self.send_header(rep.H_CURSOR, str(cursor))
        self.send_header(rep.H_EPOCH, str(self.replication.epoch))
        if wire:
            self.send_header(rep.H_CODEC, wire)
        self.end_headers()
        self.wfile.write(body)

    def _redirect_to_leader(self) -> bool:
        """Follower write redirect: a write verb landing on a follower
        apiserver answers 307 with the leader's URL (Location header +
        reply body) — RemoteStore retries the write there while its reads
        stay here. False when this server takes writes itself."""
        if not getattr(self.store, "follower", False):
            return False
        self._reply_redirect()
        return True

    def _reply_redirect(self) -> None:
        leader = ""
        if self.replication is not None:
            leader = getattr(self.replication, "leader_url", "") or ""
        # drain the request body first: leaving it unread would desync
        # the keep-alive connection's framing for the next request
        length = int(self.headers.get("Content-Length", 0) or 0)
        if length:
            self.rfile.read(length)
        wire = self._reply_codec()
        body = codec.dumps({
            "error": "follower apiserver: writes go to the leader",
            "leader": leader,
        }, wire)
        self.metrics.count_wire(wire, "out", len(body))
        self._status = 307
        self.send_response(307)
        if leader:
            self.send_header("Location", leader + self.path)
        self.send_header("Content-Type", codec.content_type_for(wire))
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802
        if not urlsplit(self.path).path.startswith(PREFIX):
            if not self._serve_replication("GET"):
                if not self._serve_collector("GET"):
                    self._serve_diagnostics()
            return
        kind, key, q = self._route()
        if kind is None:
            if q.get("watch") and q.get("buckets"):
                # batched multi-kind watch poll: N informer cursors, one
                # round trip (long-running like every watch)
                with self._track_span("WATCH", "multi", long_running=True):
                    try:
                        self._watch_bulk(q)
                    except ValueError as e:
                        self._error(400, str(e))
                    except Exception as e:
                        self._error(500, f"{type(e).__name__}: {e}")
                return
            self._error(404, "unknown path")
            return
        if key is None and q.get("watch"):
            verb = "WATCH"
        elif key is None:
            verb = "LIST"
        else:
            verb = "GET"
        # EVERY watch is long-running (the reference's longrunning
        # predicate covers long-polls too): a blocked wait_for must not
        # hold the in-flight gauge
        with self._track_span(verb, kind, long_running=(verb == "WATCH")):
            self._do_get(kind, key, q)

    def _do_get(self, kind, key, q) -> None:
        try:
            if key is None and q.get("watch"):
                if q.get("stream"):
                    self._watch_stream(kind, q)
                else:
                    self._watch(kind, q)
            elif key is None:
                self._list(kind, q)
            else:
                obj, rv = self.store.get(kind, key)
                if obj is None:
                    self._error(404, f"{kind}/{key} not found")
                else:
                    self._reply({"object": obj, "resourceVersion": rv})
        except ValueError as e:
            # malformed selector / resourceVersion: the CLIENT's error —
            # a retry-on-5xx loop must not hammer a permanently-bad request
            self._error(400, str(e))
        except Exception as e:
            self._error(500, f"{type(e).__name__}: {e}")

    def _list_lag_records(self) -> int:
        """The replication lag (in records) a bounded-staleness read may
        trail the leader by right now — 0 on an unreplicated server or
        the leader itself (their watch cache IS the write path)."""
        if not getattr(self.store, "follower", False):
            return 0
        status = getattr(self.replication, "status", None)
        if status is None:
            return 0
        try:
            return int(status().get("lagRecords", 0) or 0)
        except Exception:  # noqa: BLE001 — lag surfacing must not 500 a read
            return 0

    def _list(self, kind: str, q: dict) -> None:
        """GET /apis/<kind> — the (paged) LIST. ``limit`` caps the page
        size; a truncated page's reply carries an opaque ``continue``
        token pinned to the walk's resourceVersion snapshot, and a token
        whose snapshot fell behind the event ring's compaction horizon
        410s into a fresh walk (the reference's expired-continue
        semantics). Pages splice cached item bodies off the
        serialize-once cache — nothing re-encodes on a relist walk
        unless the object changed. ``resourceVersion=0`` is the
        bounded-staleness read: served from the local watch-ring-backed
        cache (on a follower, the replica) with the observed replication
        lag surfaced as ``store_list_lag_records``; ``maxLagRecords``
        declares the client's bound (503 when exceeded). Exact/absent-rv
        lists keep their pre-pagination semantics and bytes."""
        ls = q.get("labelSelector", "")
        fs = q.get("fieldSelector", "")
        limit = int(q.get("limit", 0))
        if limit < 0:
            raise ValueError(f"limit must be >= 0, got {limit}")
        token = q.get("continue", "")
        if q.get("resourceVersion", "") == "0":
            lag = self._list_lag_records()
            self.metrics.list_lag_last = lag
            max_lag = q.get("maxLagRecords")
            if max_lag is not None and lag > int(max_lag):
                self._error(
                    503,
                    f"bounded-staleness list lag {lag} records exceeds "
                    f"declared maxLagRecords {max_lag}",
                )
                return
        pager = getattr(self.store, "list_page", None)
        if pager is None or (limit <= 0 and not token):
            # the unpaged reply — byte-identical to the pre-pagination
            # wire (and therefore to a lag-0 rv=0 read of the same state)
            items, rv = self.store.list(
                kind, label_selector=ls, field_selector=fs,
            )
            if items:
                # a non-empty list proves the kind exists; an empty
                # 200 proves nothing (MemStore lists unknown kinds as
                # empty), so bare LIST successes never admit labels
                self.metrics.admit_resource(kind)
            self.metrics.list_pages.labels("full").inc()
            self._reply({
                "items": [
                    {"key": k, "object": o} for k, o in items
                ],
                "resourceVersion": rv,
            })
            return
        after_seq = 0
        through_seq = 0
        snapshot_rv = None
        if token:
            # malformed → ValueError → the caller's 400 (a retry loop
            # must not hammer a permanently-bad token); EXPIRED → 410
            snapshot_rv, after_seq, token_gen, through_seq = (
                codec.decode_continue(token)
            )
            horizon = self.store.compacted_through
            if snapshot_rv < horizon:
                self._error(
                    410,
                    f"continue token snapshot rv {snapshot_rv} compacted "
                    f"(through {horizon}) — restart the paged walk",
                )
                return
            store_gen = getattr(self.store, "list_generation", 0)
            if token_gen != store_gen:
                # seqs renumbered since the token was minted (crash
                # recovery / replica resync loaded a snapshot): the
                # cursor would silently skip or duplicate across the
                # renumbering, so expire it even when its rv clears the
                # compaction horizon
                self._error(
                    410,
                    "continue token predates a store snapshot load "
                    "(seq numbering reset) — restart the paged walk",
                )
                return
        wire = self._reply_codec()
        # the first page captures the walk's seq bound (echoed back by
        # the store) — later pages carry it in the token, so an object
        # created mid-walk (higher seq) can never splice into the cut
        items, store_rv, next_seq, has_more, through_seq = pager(
            kind, label_selector=ls, field_selector=fs,
            limit=limit, after_seq=after_seq, through_seq=through_seq,
        )
        if snapshot_rv is None:
            snapshot_rv = store_rv
        if items:
            self.metrics.admit_resource(kind)
        parts = [
            self.event_cache.item_bytes(kind, k, o, orv, wire)
            for k, o, orv in items
        ]
        cont = (
            codec.encode_continue(
                snapshot_rv, next_seq,
                getattr(self.store, "list_generation", 0),
                through_seq,
            )
            if has_more else None
        )
        self.metrics.list_pages.labels("paged").inc()
        self._reply_wire(
            codec.items_envelope(parts, snapshot_rv, wire, cont), wire,
        )

    @staticmethod
    def _selector_view(q: dict):
        """Per-watch SelectorView, or None without selectors. The streaming
        watch holds ONE view for the connection's lifetime (repeat foreign
        events are dropped); a long-poll request gets a fresh view each
        time (stateless protocol — degraded to one tombstone per foreign
        key per poll, still correct)."""
        from ..store.memstore import SelectorView

        ls = q.get("labelSelector", "")
        fs = q.get("fieldSelector", "")
        return SelectorView(ls, fs) if (ls or fs) else None

    def _event_bytes(self, e, scoped: bool, wire: str) -> bytes:
        """One event's wire body, always through the serialize-once cache.
        A scoped DELETED (including a selector REWRITE of the original
        event) ships no object body — the cache's ``tombstone`` key
        dimension keeps it distinct from the unscoped full-body entry
        while still sharing ONE encoding across every scoped watcher."""
        if scoped and e.type == "DELETED":
            # selector-scoped stream: never ship a body on DELETED (the
            # informer deletes by key; a tombstoned object may not even
            # match the selector)
            return self.event_cache.event_bytes(e, wire, tombstone=True)
        return self.event_cache.event_bytes(e, wire)

    def _events_body(self, events, cursor: int, scoped: bool,
                     wire: str) -> bytes:
        """The long-poll reply (and a batched-poll bucket) assembled by
        SPLICING cached event bytes — no event re-encodes on fan-out."""
        return codec.events_envelope(
            [self._event_bytes(e, scoped, wire) for e in events],
            cursor, wire,
        )

    def _watch(self, kind: str, q: dict) -> None:
        wire = self._reply_codec()
        rv = int(q.get("resourceVersion", 0))
        timeout = min(float(q.get("timeoutSeconds", 10)), 60.0)
        view = self._selector_view(q)
        # unscoped fast path: the store's per-event body ring hands back
        # cached wire bodies directly — no WatchEvent is ever materialized
        # on the fan-out path (the native core's list/watch hot loop)
        body_since = (
            getattr(self.store, "events_body_since", None)
            if view is None else None
        )
        try:
            if body_since is not None:
                parts, cursor = body_since(kind, rv, wire)
                if not parts and timeout > 0:
                    self.store.wait_for(rv, timeout=timeout)
                    parts, cursor = body_since(kind, rv, wire)
                body = codec.events_envelope(parts, cursor, wire)
            else:
                events, cursor = self.store._events_since(kind, rv)
                if not events and timeout > 0:
                    self.store.wait_for(rv, timeout=timeout)
                    events, cursor = self.store._events_since(kind, rv)
                if view is not None:
                    events = view.filter(events)
                body = self._events_body(events, cursor, view is not None,
                                         wire)
        except CompactedError as e:
            # the watch cache's "too old resource version" → HTTP 410
            self._error(410, str(e))
            return
        self._reply_wire(body, wire)

    def _drain_buckets(self, buckets: dict, wire: str):
        """One drain of every bucket's cursor → ({kind: (event bodies,
        cursor) | CompactedError}, drain revision). Uses the store's
        body-ring bulk drain when it has one (ONE lock round, cached
        bodies, zero WatchEvent churn); otherwise materializes through
        ``events_since_bulk`` + the serialize-once cache."""
        bulk_bodies = getattr(self.store, "events_body_since_bulk", None)
        if bulk_bodies is not None:
            return bulk_bodies(buckets, wire)
        results, drain_rv = self.store.events_since_bulk(buckets)
        out: dict = {}
        for kind, res in results.items():
            if isinstance(res, CompactedError):
                out[kind] = res
                continue
            events, cursor = res
            out[kind] = (
                [self._event_bytes(e, False, wire) for e in events],
                cursor,
            )
        return out, drain_rv

    def _watch_bulk(self, q: dict) -> None:
        """Batched watch poll: ``buckets=pods:12,nodes:7`` drains every
        kind's cursor — ONE store lock acquisition, ONE HTTP round trip —
        with per-kind results (a compacted cursor 410s only its own
        bucket). Selectors are not supported on the batched poll (the
        per-kind endpoint remains for scoped watchers)."""
        wire = self._reply_codec()
        buckets: dict[str, int] = {}
        for part in q["buckets"].split(","):
            kind, sep, rv = part.rpartition(":")
            if not sep or not kind:
                raise ValueError(f"malformed bucket {part!r} (want kind:rv)")
            buckets[kind] = int(rv)
        timeout = min(float(q.get("timeoutSeconds", 0)), 60.0)
        results, drain_rv = self._drain_buckets(buckets, wire)
        if timeout > 0 and not any(
            isinstance(r, CompactedError) or r[0]
            for r in results.values()
        ):
            # wait on the revision captured AT the drain (same lock round):
            # a write landing after the drain wakes this immediately
            self.store.wait_for(drain_rv, timeout=timeout)
            results, _ = self._drain_buckets(buckets, wire)
        parts = []
        for kind in buckets:
            res = results[kind]
            if isinstance(res, CompactedError):
                body = codec.dumps({"error": str(res), "code": 410}, wire)
            else:
                bodies, cursor = res
                body = codec.events_envelope(bodies, cursor, wire)
            parts.append((kind, body))
        self._reply_wire(codec.buckets_envelope(parts, wire), wire)

    def _watch_stream(self, kind: str, q: dict) -> None:
        """Chunked watch stream: events written as they happen, connection
        held open up to ``timeoutSeconds`` (capped) — the watch-stream form
        of the same cursor protocol. JSON streams are ndjson (one event
        per line); a negotiated binary stream is u32-length-prefixed
        frames (``application/x-kubetpu-bin-seq``). A compaction
        mid-stream emits an error frame with code 410 and ends the stream
        (client relists)."""
        import time as _time

        wire = self._reply_codec()
        rv = int(q.get("resourceVersion", 0))
        timeout = min(float(q.get("timeoutSeconds", 30)), 300.0)
        try:
            view = self._selector_view(q)
        except ValueError as e:
            self._error(400, str(e))
            return
        body_since = (
            getattr(self.store, "events_body_since", None)
            if view is None else None
        )
        deadline = _time.monotonic() + timeout
        self._status = 200
        self.send_response(200)
        self.send_header("Content-Type", (
            codec.binary_stream_content_type()
            if wire == codec.BINARY else "application/x-ndjson"
        ))
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def chunk_bytes(data: bytes) -> bool:
            self.metrics.count_wire(wire, "out", len(data))
            try:
                self.wfile.write(f"{len(data):x}\r\n".encode())
                self.wfile.write(data + b"\r\n")
                self.wfile.flush()
                return True
            except (BrokenPipeError, ConnectionResetError, OSError):
                return False

        def frame(body: bytes) -> bool:
            return chunk_bytes(codec.stream_frame(body, wire))
        try:
            while True:
                try:
                    if body_since is not None:
                        # unscoped: cached bodies straight off the store's
                        # body ring — no WatchEvent materialization
                        bodies, cursor = body_since(kind, rv, wire)
                    else:
                        events, cursor = self.store._events_since(kind, rv)
                        if view is not None:
                            events = view.filter(events)
                        bodies = [
                            self._event_bytes(e, view is not None, wire)
                            for e in events
                        ]
                except CompactedError as e:
                    frame(codec.dumps({"error": str(e), "code": 410}, wire))
                    break
                for body in bodies:
                    # stream frames share the serialize-once cache with the
                    # poll paths — one encode serves every watcher
                    if not frame(body):
                        return   # client hung up: no terminator possible
                rv = cursor
                remaining = deadline - _time.monotonic()
                if remaining <= 0 or getattr(self.server, "closing", False):
                    break
                self.store.wait_for(rv, timeout=min(remaining, 1.0))
        finally:
            try:
                self.wfile.write(b"0\r\n\r\n")   # chunked terminator
                self.wfile.flush()
            except OSError:
                pass

    # -------------------------------------------------- shared verb cores
    # decode → admission → storage for one object, shared verbatim by the
    # single-op handlers and the bulk sequential path (the write path of
    # registry/store.go:514) — one copy, so the two surfaces cannot drift

    def _apply_create(self, kind: str, key: str, payload) -> int:
        # as_object: a binary body already materialized the typed object;
        # a JSON body left the kind-tagged dict — one normalization point
        obj = _stamp_pod_ingest(kind, codec.as_object(payload))
        self._note_pod_trace(kind, obj)     # ingest span ↔ pod timeline
        # the admission chain's write locks span admit AND create so a
        # usage-counting validator (quota) cannot race a concurrent
        # create of the same scope
        with self.registry.locked(kind, key, obj, verb="create"):
            obj = self.registry.admit(kind, key, obj, verb="create")
            return self.store.create(kind, key, obj)

    def _apply_update(
        self, kind: str, key: str, payload, expect_rv: int | None
    ) -> int:
        obj = codec.as_object(payload)
        self._note_pod_trace(kind, obj)     # bind-subresource span ↔ pod
        with self.registry.locked(kind, key, obj, verb="update"):
            old, _old_rv = self.store.get(kind, key)
            obj = self.registry.admit(kind, key, obj, old=old, verb="update")
            return self.store.update(kind, key, obj, expect_rv=expect_rv)

    def do_POST(self) -> None:  # noqa: N802
        if not urlsplit(self.path).path.startswith(PREFIX):
            if not self._serve_collector("POST"):
                self._error(404, "unknown path")
            return
        if self._redirect_to_leader():
            return
        kind, key, _ = self._route()
        if kind is not None and key is None and kind.endswith(BULK_SUFFIX):
            resource = kind[: -len(BULK_SUFFIX)]
            with self._track_span("BULK", resource):
                try:
                    self._do_bulk(resource)
                except codec.UnsupportedWireError as e:
                    self._error(415, str(e))
                except FollowerWriteError:
                    # demoted mid-request (failover race): same answer as
                    # the up-front guard — go to the leader
                    self._reply_redirect()
                except Exception as e:
                    self._error(500, f"{type(e).__name__}: {e}")
            return
        if kind is None or key is None:
            self._error(404, "kind and key required")
            return
        with self._track_span("CREATE", kind):
            try:
                rv = self._apply_create(kind, key, self._read_body())
                self._reply({"resourceVersion": rv}, status=201)
            except FollowerWriteError:
                self._reply_redirect()
            except ConflictError as e:
                self._error(409, str(e))
            except ValidationError as e:
                self._error(422, str(e))
            except AdmissionDenied as e:
                self._error(403, str(e))
            except codec.UnsupportedWireError as e:
                self._error(415, str(e))
            except scheme.SchemeError as e:
                self._error(400, str(e))
            except Exception as e:
                self._error(500, f"{type(e).__name__}: {e}")

    def do_PUT(self) -> None:  # noqa: N802
        if self._redirect_to_leader():
            return
        kind, key, q = self._route()
        if kind is None or key is None:
            self._error(404, "kind and key required")
            return
        with self._track_span("UPDATE", kind):
            try:
                expect = (
                    int(q["resourceVersion"])
                    if "resourceVersion" in q else None
                )
                rv = self._apply_update(kind, key, self._read_body(), expect)
                self._reply({"resourceVersion": rv})
            except FollowerWriteError:
                self._reply_redirect()
            except ConflictError as e:
                self._error(409, str(e))
            except ValidationError as e:
                self._error(422, str(e))
            except AdmissionDenied as e:
                self._error(403, str(e))
            except codec.UnsupportedWireError as e:
                self._error(415, str(e))
            except scheme.SchemeError as e:
                self._error(400, str(e))
            except Exception as e:
                self._error(500, f"{type(e).__name__}: {e}")

    def _do_bulk(self, kind: str) -> None:
        """POST /apis/<kind>:bulk — results are positional; each op's
        status/resourceVersion/error matches what its single-op verb would
        have returned, so a mid-batch conflict or admission veto fails only
        its own op. Two execution paths, chosen by the kind's admission
        shape:

        - no dynamic admission (no hooks, no write locks — the scheduler's
          bind/status traffic): decode + strategy-validate per op, then
          apply every surviving storage write under ONE store lock
          acquisition (``MemStore.bulk``);
        - dynamic admission present (quota locks, webhooks): each op runs
          the EXACT single-verb chain sequentially — lock spans admit AND
          write, and an update's ``old`` reflects earlier ops in the same
          batch — trading the one-lock storage pass for unchanged
          admission atomicity (the round trip is still one)."""
        body = self._read_body()
        ops = body.get("ops")
        if not isinstance(ops, list):
            self._error(400, "body must carry an ops list")
            return
        if self.registry.has_dynamic_admission(kind):
            out = [self._bulk_op_sequential(kind, op) for op in ops]
            if any(r.get("status", 500) < 400 for r in out):
                self.metrics.admit_resource(kind)
            self._reply({"results": out})
            return
        results: list[dict | None] = []
        prepared: list[dict | None] = []
        for op in ops:
            verb = op.get("op") if isinstance(op, dict) else None
            key = op.get("key") if isinstance(op, dict) else None
            try:
                if not key or verb not in (
                    "create", "update", "patch", "delete", "get"
                ):
                    raise ValueError(
                        "op must carry a key and one of "
                        "create/update/patch/delete/get"
                    )
                if verb in ("create", "update", "patch"):
                    obj = codec.as_object(op.get("object") or {})
                    real = "create" if verb == "create" else "update"
                    if real == "create":
                        obj = _stamp_pod_ingest(kind, obj)
                    self._note_pod_trace(kind, obj)
                    # this path only runs WITHOUT dynamic admission, so
                    # admit() is pure strategy validation — no locker to
                    # hold, no hook to feed `old`, no per-op store read
                    obj = self.registry.admit(kind, key, obj, verb=real)
                    prepared.append({
                        "op": real, "key": key, "object": obj,
                        "expect_rv": op.get("resourceVersion"),
                    })
                else:
                    prepared.append({"op": verb, "key": key})
                results.append(None)     # filled from the storage pass
            except _OP_ERRORS as e:
                results.append(_op_error_result(e))
                prepared.append(None)
        store_ops = [p for p in prepared if p is not None]
        store_res = iter(self.store.bulk(kind, store_ops))
        any_ok = False
        out = []
        for res, prep in zip(results, prepared):
            if res is None:
                # result objects stay LIVE — the negotiated reply codec
                # encodes them in _reply (no per-op pre-serialization)
                res = dict(next(store_res))
            if res.get("status", 500) < 400:
                any_ok = True
            res.setdefault("resourceVersion", 0)
            out.append(res)
        if any_ok:
            # a 2xx op proves the kind exists (same gate as the single
            # verbs' proving responses)
            self.metrics.admit_resource(kind)
        self._reply({"results": out})

    def _bulk_op_sequential(self, kind: str, op) -> dict:
        """One bulk op through the exact single-verb chain (the dynamic-
        admission path): write lock spanning admit AND storage write,
        ``old`` read inside the lock after every earlier op applied."""
        verb = op.get("op") if isinstance(op, dict) else None
        key = op.get("key") if isinstance(op, dict) else None
        try:
            if not key or verb not in (
                "create", "update", "patch", "delete", "get"
            ):
                raise ValueError(
                    "op must carry a key and one of "
                    "create/update/patch/delete/get"
                )
            if verb == "create":
                rv = self._apply_create(kind, key, op.get("object") or {})
                return {"status": 201, "resourceVersion": rv}
            if verb in ("update", "patch"):
                rv = self._apply_update(
                    kind, key, op.get("object") or {},
                    op.get("resourceVersion"),
                )
                return {"status": 200, "resourceVersion": rv}
            if verb == "delete":
                rv = self.store.delete(kind, key)
                return {"status": 200, "resourceVersion": rv}
            obj, rv = self.store.get(kind, key)      # verb == "get"
            if obj is None:
                return {
                    "status": 404, "resourceVersion": 0,
                    "error": f"{kind}/{key} not found",
                }
            return {"status": 200, "resourceVersion": rv, "object": obj}
        except _OP_ERRORS as e:
            return _op_error_result(e)

    def do_DELETE(self) -> None:  # noqa: N802
        if self._redirect_to_leader():
            return
        kind, key, _ = self._route()
        if kind is None or key is None:
            self._error(404, "kind and key required")
            return
        with self._track_span("DELETE", kind):
            try:
                rv = self.store.delete(kind, key)
                self._reply({"resourceVersion": rv})
            except FollowerWriteError:
                self._reply_redirect()
            except KeyError:
                self._error(404, f"{kind}/{key} not found")
            except Exception as e:
                self._error(500, f"{type(e).__name__}: {e}")


class APIServer:
    """In-process HTTP front for a MemStore (httptest.NewServer shape)."""

    def __init__(
        self, store: MemStore | None = None,
        host: str = "127.0.0.1", port: int = 0,
        registry: Registry | None = None,
        metrics_sources: tuple = (),
        wire: str = "binary",
        persistence: "str | None" = None,
        collector: bool = False,
        sentinel: "bool | object" = False,
        replication: "object | None" = None,
    ) -> None:
        """``metrics_sources``: extra Prometheus-text providers appended to
        GET /metrics (e.g. a co-hosted controller family's workqueue set).
        ``wire``: "binary" (default) negotiates the compact binary codec
        per request via Accept/Content-Type; "json" is the escape hatch —
        a JSON-only server that ignores binary Accept headers and 415s
        binary bodies (exactly what a pre-binary server build does, so
        mixed-version client/server pairs are testable).
        ``collector``: mount the embedded telemetry collector on this
        server's listener (/telemetry/export /telemetry/clock
        /telemetry/trace /telemetry/metrics /telemetry/flightrecorder
        /telemetry/top) — the apiserver doubles as the cluster's span/
        metrics sink, the ``kubetpu collector``-less deployment shape.
        ``sentinel``: embed the anomaly sentinel (telemetry.sentinel) —
        ``True`` builds one over the default rule table (or pass a
        pre-built ``Sentinel``), bound to THIS server's /metrics text
        (request histograms + the WAL fsync set), evaluated by a cadence
        thread (``start()`` spawns it), and served at /debug/alerts +
        /debug/bundle next to the other diagnostics.
        ``persistence``: a directory path makes the server's store durable
        (``--persistence dir``): recover-on-start replays the WAL +
        snapshot, every committed write is logged-then-applied, and
        ``close()`` flushes the log so a graceful stop never leaves a
        torn tail. Ignored when an existing ``store`` is passed in — its
        durability is the caller's choice.
        ``replication``: a pre-built replication role
        (``store.replication.LeaderLease`` over this server's own store,
        or a ``FollowerReplicator`` tailing a leader into it) — mounts
        /replication/log, /replication/snapshot, /replication/status,
        turns on the follower write redirect, and adds the role's metrics
        to /metrics. ``start()``/``close()`` run its lifecycle. ``None``
        (the default) leaves the server exactly as before — the
        single-apiserver escape hatch."""
        if wire not in ("binary", "json"):
            raise ValueError(f"wire must be binary|json, got {wire!r}")
        # close() tears down only a store THIS server created — a passed-in
        # store's lifecycle (and durability) stays the caller's
        self._owns_store = store is None
        self.store = (
            store if store is not None else MemStore(persistence=persistence)
        )
        self.registry = registry if registry is not None else Registry()
        self.metrics = APIServerMetrics()
        self.health = HealthChecks()
        # the storage-backend check (the reference's etcd check): probing
        # the store's revision counter exercises its lock + native core
        def _store_check() -> None:
            rv = self.store.resource_version   # property on MemStore
            if callable(rv):                   # method on store stand-ins
                rv()

        # healthz/readyz only — the reference excludes its etcd check
        # from /livez: a storage outage must mark the server NOT-READY,
        # not not-alive, or a liveness probe restart-loops a process
        # that is still serving watches
        self.health.add_check(
            "store", _store_check, endpoints=("healthz", "readyz")
        )
        # serialize-once watch fan-out: one wire encode per event per
        # codec, shared across every watcher poll, batched poll, and
        # stream frame (the store binding merges the native body ring's
        # hit/miss counters into the exposed numbers)
        self.event_cache = EventEncodeCache(store=self.store)
        # server spans: one per request through the _track_span seam,
        # joined to client spans via the propagated traceparent; drained
        # by the telemetry exporter, browsable at /trace
        from ..tracing import Tracer

        self.tracer = Tracer(max_spans=8192)
        self.collector = None
        if collector:
            from ..telemetry.collector import Collector

            self.collector = Collector()
        # durable-store observability: the WAL's fsync histogram +
        # segment/byte/snapshot-age gauges ride this server's /metrics
        # (a memory-only store exposes nothing)
        wal_sources: tuple = ()
        if getattr(self.store, "persistent", False):
            wal_text = getattr(self.store, "wal_metrics_text", None)
            if callable(wal_text):
                wal_sources = (wal_text,)

        def _event_cache_metrics() -> str:
            stats = self.event_cache.stats_by_codec()
            lines = [
                "# HELP apiserver_watch_event_encodings_total Watch event "
                "wire serializations by outcome and codec (hit = cached "
                "bytes reused across watchers).\n"
                "# TYPE apiserver_watch_event_encodings_total counter\n"
            ]
            for c in sorted(stats):
                h, m = stats[c]
                lines.append(
                    "apiserver_watch_event_encodings_total"
                    f"{{result=\"hit\",codec=\"{c}\"}} {h}\n"
                    "apiserver_watch_event_encodings_total"
                    f"{{result=\"miss\",codec=\"{c}\"}} {m}\n"
                )
            return "".join(lines)

        def _list_lag_metrics() -> str:
            # bounded-staleness read plane: the replication lag (records)
            # the last rv=0 list was served at. Emitted ONLY on a live
            # follower — unreplicated/leader servers omit the series, so
            # the sentinel's list-lag rule stays dormant there (same
            # contract as store_replication_lag_records)
            if not getattr(self.store, "follower", False):
                return ""
            lag = self.metrics.list_lag_last
            if lag is None:
                return ""
            return (
                "# HELP store_list_lag_records Replication records the "
                "last rv=0 (bounded-staleness) list trailed the leader "
                "by.\n"
                "# TYPE store_list_lag_records gauge\n"
                f"store_list_lag_records {lag}\n"
            )

        # embedded anomaly sentinel: watches THIS server's own scrape
        # (request histograms + the WAL fsync set) on a cadence thread
        self.sentinel = None
        if sentinel:
            from ..telemetry.sentinel import Sentinel

            self.sentinel = (
                sentinel if isinstance(sentinel, Sentinel) else Sentinel()
            )
            bundle_sources: dict = {}
            wal_stats = getattr(self.store, "wal_stats", None)
            if callable(wal_stats):
                bundle_sources["wal"] = wal_stats
            bundle_sources["event_cache"] = self.event_cache.stats_by_codec
            self.sentinel.bind(
                metrics_fn=self.metrics_text,
                tracer=self.tracer,
                bundle_sources=bundle_sources,
                process="apiserver",
                component="apiserver",
            )
        sentinel_sources: tuple = ()
        if self.sentinel is not None:
            sentinel_sources = (self.sentinel.metrics_text,)
        # the replication role's gauges (lag/epoch/applied) ride this
        # server's /metrics — the sentinel's replication_lag rule and the
        # telemetry exporter both read them from here
        self.replication = replication
        rep_sources: tuple = ()
        if replication is not None:
            rep_text = getattr(replication, "metrics_text", None)
            if callable(rep_text):
                rep_sources = (rep_text,)
        self._metrics_sources = (
            _event_cache_metrics, _list_lag_metrics, *wal_sources,
            *rep_sources, *sentinel_sources, *metrics_sources,
        )
        handler = type("BoundHandler", (_Handler,), {
            "store": self.store, "registry": self.registry,
            "metrics": self.metrics, "health": self.health,
            "event_cache": self.event_cache,
            "tracer": self.tracer,
            "collector": self.collector,
            "sentinel": self.sentinel,
            "replication": self.replication,
            "wire_enabled": wire == "binary",
            "metrics_sources": self._metrics_sources,
            # responses are small; Nagle + the client's delayed ACK would
            # stall every keep-alive request ~40 ms (a handler-class knob:
            # socketserver.StreamRequestHandler.disable_nagle_algorithm)
            "disable_nagle_algorithm": True,
        })

        class _Server(ThreadingHTTPServer):
            # streaming watch handlers hold connections open (bounded by
            # their own deadlines + the `closing` flag, checked every ≤1 s);
            # close() must not block on them
            daemon_threads = True
            block_on_close = False
            closing = False

            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                self._conn_lock = threading.Lock()
                self._conns: set = set()

            def get_request(self):
                sock, addr = super().get_request()
                with self._conn_lock:
                    self._conns.add(sock)
                return sock, addr

            def shutdown_request(self, request):
                with self._conn_lock:
                    self._conns.discard(request)
                super().shutdown_request(request)

            def sever(self) -> None:
                """Half-close every live connection: a handler blocked on
                the next keep-alive request reads EOF and exits cleanly,
                so a closed server is DOWN for clients that already held a
                connection — without this, keep-alive handler threads
                outlive close() and a 'killed' leader keeps serving its
                replication feed (failover never sees the death)."""
                import socket as _socket

                with self._conn_lock:
                    conns = list(self._conns)
                for sock in conns:
                    try:
                        sock.shutdown(_socket.SHUT_RDWR)
                    except OSError:
                        pass

            def handle_error(self, request, client_address):
                if not self.closing:
                    super().handle_error(request, client_address)

        self._httpd = _Server((host, port), handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def attach_replication(self, replication) -> None:
        """Bind a replication role AFTER construction — the leader's lease
        identity is its own URL, which exists only once the listener is
        bound (``--port 0``). Must run before ``start()``: mounts the
        /replication/* endpoints, the follower write redirect, and the
        role's metrics, exactly as the constructor param would."""
        self.replication = replication
        self._httpd.RequestHandlerClass.replication = replication
        rep_text = getattr(replication, "metrics_text", None)
        if callable(rep_text) and rep_text not in self._metrics_sources:
            # keep the constructor's source order: the role's gauges sit
            # right after the store/WAL set, before the sentinel's
            self._metrics_sources = (
                *self._metrics_sources[:1], rep_text,
                *self._metrics_sources[1:],
            )
            self._httpd.RequestHandlerClass.metrics_sources = (
                self._metrics_sources
            )

    def metrics_text(self) -> str:
        """The same Prometheus text GET /metrics serves (request set +
        event-cache counters + WAL set + extra sources) — the telemetry
        exporter's snapshot source."""
        chunks = [self.metrics.expose()]
        for source in self._metrics_sources:
            chunks.append(source())
        return "".join(chunks)

    def start(self) -> "APIServer":
        self._thread.start()
        if self.replication is not None:
            # leader: take the writer lease before serving writes;
            # follower: start the tail (the listener is already up, so a
            # peer's status probe can reach us during bootstrap)
            self.replication.start()
        if self.sentinel is not None:
            # thread-served owner: the sentinel runs its own cadence
            # (the scheduler instead evaluates at its cycle boundary)
            self.sentinel.start()
        return self

    def close(self) -> None:
        if self.replication is not None:
            # stop the renew/tail thread while the store and peers are
            # still reachable (a leader releases the writer lease here)
            self.replication.close()
        if self.sentinel is not None:
            self.sentinel.close()
        self._httpd.closing = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd.sever()
        self._thread.join(timeout=5)
        # AFTER the listener is down (no request can append mid-close):
        # flush + fsync + close an OWNED store's WAL, so a graceful stop
        # never leaves a torn tail for the next boot's recovery to
        # truncate. A caller-provided store stays open — its durability
        # and lifecycle are the caller's (writes after OUR close must not
        # silently stop reaching its log)
        if self._owns_store:
            close_store = getattr(self.store, "close", None)
            if callable(close_store):
                close_store()
