"""The API server layer: REST + watch over the store, and the remote
store client components use across process boundaries."""

from .server import APIServer  # noqa: F401
from .remote import RemoteStore  # noqa: F401
