"""The API server layer: REST + watch over the store, and the remote
store client components use across process boundaries."""

from .admission import AdmissionDenied, Registry, ValidationError  # noqa: F401
from .server import APIServer  # noqa: F401
from .remote import RemoteStore  # noqa: F401
