"""Admission + per-kind validation — the apiserver's write-path gate.

Reference: a write is decode → admission (mutating → validating chain) →
strategy validation → storage (`DefaultBuildHandlerChain` +
``registerResourceHandlers`` feeding the generic registry Store, whose
``Create``/``Update`` run the per-resource strategy —
staging/src/k8s.io/apiserver/pkg/registry/generic/registry/store.go:514;
strategies under the reference's ``pkg/registry/<group>/<kind>/strategy.go``
with validation in ``pkg/apis/<group>/validation``). Here:

- ``Registry.admit(kind, key, obj, old, verb)`` runs the MUTATING hooks
  (each may return a replacement object — the MutatingAdmissionWebhook /
  defaulting seam), then the kind's validation strategy (invalid object →
  ``ValidationError`` → HTTP 422, the reference's Unprocessable Entity for
  field validation failures), then the VALIDATING hooks (policy veto →
  ``AdmissionDenied`` → HTTP 403, the ValidatingAdmissionWebhook shape).
- Strategies are per-KIND functions over the typed envelope; the default
  registry covers every bucket the framework serves, with the reference's
  load-bearing field rules (a name is required and must agree with the
  URL key; resource quantities are non-negative; replicas/parallelism
  bounds; maxSurge+maxUnavailable not both zero; PDB minAvailable XOR
  maxUnavailable; topology-spread maxSkew ≥ 1 — pkg/apis/core/validation,
  pkg/apis/apps/validation, pkg/apis/policy/validation).

The in-process ``MemStore`` API deliberately bypasses this (that path is
the reference's "write to etcd directly"); everything arriving over REST —
every separate-process component — is gated.
"""

from __future__ import annotations

from contextlib import ExitStack, contextmanager
from typing import Any, Callable, Iterable

from ..api import types as t

POD_PHASES = {"", "Pending", "Running", "Succeeded", "Failed", "Unknown"}


class ValidationError(ValueError):
    """Strategy validation failure → 422 Unprocessable Entity."""

    status = 422

    def __init__(self, kind: str, key: str, errors: list[str]) -> None:
        self.errors = errors
        super().__init__(f"{kind}/{key} invalid: " + "; ".join(errors))


class AdmissionDenied(Exception):
    """Validating-hook veto → 403 Forbidden (admission webhook deny)."""

    status = 403


def _name_key_agree(obj: Any, key: str, errs: list[str]) -> None:
    name = getattr(obj, "name", None)
    if name is not None:
        if not name:
            errs.append("metadata.name is required")
            return
        namespace = getattr(obj, "namespace", None)
        natural = f"{namespace}/{name}" if namespace is not None else name
        if key != natural:
            errs.append(
                f"the name in the URL ({key!r}) does not match the "
                f"object ({natural!r})"
            )


def _non_negative(pairs: Iterable[tuple[str, int]], what: str,
                  errs: list[str]) -> None:
    for k, v in pairs:
        if v < 0:
            errs.append(f"{what}[{k}]: must be non-negative, got {v}")


def validate_pod(pod: t.Pod, errs: list[str]) -> None:
    _non_negative(pod.requests, "spec.requests", errs)
    if pod.phase not in POD_PHASES:
        errs.append(f"status.phase: unknown phase {pod.phase!r}")
    for c in pod.topology_spread_constraints:
        if c.max_skew < 1:
            errs.append("topologySpreadConstraints.maxSkew: must be >= 1")
        if not c.topology_key:
            errs.append("topologySpreadConstraints.topologyKey is required")
    for port in pod.ports:
        if not (0 < port.host_port <= 65535):
            errs.append(f"hostPort {port.host_port}: out of range")
    if pod.priority < -(2**31) or pod.priority >= 2**31:
        errs.append("spec.priority: out of int32 range")


def validate_node(node: t.Node, errs: list[str]) -> None:
    _non_negative(node.allocatable, "status.allocatable", errs)


def _validate_workload(obj: Any, errs: list[str]) -> None:
    if getattr(obj, "replicas", 0) < 0:
        errs.append("spec.replicas: must be non-negative")
    sel = getattr(obj, "selector", None)
    tpl = getattr(obj, "template", None)
    if sel is not None and tpl is not None:
        from ..api.selectors import label_selector_matches

        if not label_selector_matches(sel, tpl.labels_dict()):
            # apps validation: template labels must satisfy the selector,
            # or the controller could never claim its own pods
            errs.append("spec.template.metadata.labels: must match selector")


def validate_deployment(dep: t.Deployment, errs: list[str]) -> None:
    _validate_workload(dep, errs)
    if dep.strategy not in ("RollingUpdate", "Recreate"):
        errs.append(f"spec.strategy: unknown strategy {dep.strategy!r}")
    if dep.max_surge < 0 or dep.max_unavailable < 0:
        errs.append("maxSurge/maxUnavailable: must be non-negative")
    elif (dep.strategy == "RollingUpdate"
          and dep.max_surge == 0 and dep.max_unavailable == 0):
        errs.append("maxSurge and maxUnavailable may not both be zero")


def validate_job(job: t.Job, errs: list[str]) -> None:
    if job.completions < 0:
        errs.append("spec.completions: must be non-negative")
    if job.parallelism < 0:
        errs.append("spec.parallelism: must be non-negative")
    if job.backoff_limit < 0:
        errs.append("spec.backoffLimit: must be non-negative")
    if job.succeeded < 0 or job.failed < 0:
        errs.append("status counts must be non-negative")


def validate_statefulset(ss: t.StatefulSet, errs: list[str]) -> None:
    _validate_workload(ss, errs)
    if ss.pod_management_policy not in ("OrderedReady", "Parallel"):
        errs.append(
            f"spec.podManagementPolicy: unknown {ss.pod_management_policy!r}"
        )


def validate_pdb(pdb: t.PodDisruptionBudget, errs: list[str]) -> None:
    if pdb.min_available is not None and pdb.max_unavailable is not None:
        errs.append("minAvailable and maxUnavailable are mutually exclusive")
    for v in (pdb.min_available, pdb.max_unavailable):
        if v is not None and v < 0:
            errs.append("PDB thresholds must be non-negative")


def validate_resource_claim(claim: t.ResourceClaim, errs: list[str]) -> None:
    for req in claim.requests:
        if not req.name:
            errs.append("spec.devices.requests[].name is required")
        if req.count < 1:
            errs.append(
                f"request {req.name!r}: count must be >= 1, got {req.count}"
            )


def validate_resource_slice(sl: t.ResourceSlice, errs: list[str]) -> None:
    if not sl.driver:
        errs.append("spec.driver is required")
    modes = sum((bool(sl.node_name), sl.all_nodes, sl.node_selector is not None))
    if modes > 1:
        errs.append(
            "nodeName / allNodes / nodeSelector are mutually exclusive"
        )


_VALIDATORS: dict[type, Callable[[Any, list[str]], None]] = {
    t.Pod: validate_pod,
    t.Node: validate_node,
    t.ReplicaSet: _validate_workload,
    t.Deployment: validate_deployment,
    t.Job: validate_job,
    t.StatefulSet: validate_statefulset,
    t.DaemonSet: _validate_workload,
    t.PodDisruptionBudget: validate_pdb,
    t.ResourceClaim: validate_resource_claim,
    t.ResourceSlice: validate_resource_slice,
}


class Registry:
    """The admission chain + strategy dispatcher for one server."""

    def __init__(self) -> None:
        # hook: fn(kind, key, obj, old) — mutating returns obj|None,
        # validating raises AdmissionDenied; ``kinds=None`` = every kind
        self._mutating: list[tuple[Callable, set[str] | None]] = []
        self._validating: list[tuple[Callable, set[str] | None]] = []
        # locker: fn(kind, key, obj, verb) -> context manager | None; the
        # apiserver holds every matching lock across admit AND the storage
        # write, so a usage-counting validator (quota) sees check+create as
        # one atomic step (the reference's locked quota reservation)
        self._lockers: list[tuple[Callable, set[str] | None]] = []

    def add_mutating_hook(
        self, fn: Callable, kinds: Iterable[str] | None = None
    ) -> None:
        self._mutating.append((fn, set(kinds) if kinds else None))

    def add_validating_hook(
        self, fn: Callable, kinds: Iterable[str] | None = None
    ) -> None:
        self._validating.append((fn, set(kinds) if kinds else None))

    def add_write_lock(
        self, fn: Callable, kinds: Iterable[str] | None = None
    ) -> None:
        """Register a write-lock provider: ``fn(kind, key, obj, verb)``
        returns a context manager (a ``threading.Lock`` works) scoping the
        write, or None to pass."""
        self._lockers.append((fn, set(kinds) if kinds else None))

    def has_dynamic_admission(self, kind: str) -> bool:
        """True when any mutating/validating hook or write-lock provider
        matches ``kind``. The bulk verb's one-lock storage fast path is
        only sound for kinds WITHOUT dynamic admission (a usage-counting
        validator like quota must see each admit+write as one atomic step,
        and an update hook's ``old`` must reflect earlier ops in the same
        batch) — such kinds run the batch through the sequential
        single-verb chain instead."""
        for _fn, kinds in (
            *self._mutating, *self._validating, *self._lockers,
        ):
            if kinds is None or kind in kinds:
                return True
        return False

    @contextmanager
    def locked(self, kind: str, key: str, obj: Any, verb: str = "create"):
        """Every matching write lock held, in registration order, for the
        duration of the admit + store write."""
        with ExitStack() as stack:
            for fn, kinds in self._lockers:
                if kinds is None or kind in kinds:
                    cm = fn(kind, key, obj, verb)
                    if cm is not None:
                        stack.enter_context(cm)
            yield

    def admit(
        self, kind: str, key: str, obj: Any, old: Any = None,
        verb: str = "create",
    ) -> Any:
        """Mutate → validate strategy → validating hooks. Returns the
        (possibly mutated) object to store, or raises."""
        for fn, kinds in self._mutating:
            if kinds is None or kind in kinds:
                replacement = fn(kind, key, obj, old)
                if replacement is not None:
                    obj = replacement
        errs: list[str] = []
        _name_key_agree(obj, key, errs)
        validator = _VALIDATORS.get(type(obj))
        if validator is not None:
            validator(obj, errs)
        if errs:
            raise ValidationError(kind, key, errs)
        for fn, kinds in self._validating:
            if kinds is None or kind in kinds:
                fn(kind, key, obj, old)
        return obj
